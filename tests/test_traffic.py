"""Traffic subsystem tests: seeded-replay determinism, sampling cost and
distribution pins, bounded-mempool overload behavior, per-tx lifecycle
accounting, and the engine/obs/tooling integration seams.

The seeded-replay contract mirrors tests/test_scenarios.py: same seed ⇒
identical arrival schedule, identical sampled proposals, identical
Batches (digest), identical latency histograms.
"""

import hashlib
import json
import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.engine import ArrayHoneyBadgerNet
from hbbft_tpu.obs.health import HealthReporter, why_stalled
from hbbft_tpu.protocols.transaction_queue import RemovalAccount, TransactionQueue
from hbbft_tpu.traffic import (
    ArrayTrafficDriver,
    BoundedMempool,
    ClosedLoopSource,
    ObjectTrafficDriver,
    OpenLoopSource,
    PayloadSizes,
    TxTracker,
    ZipfPopulation,
    make_tx,
)


# ---------------------------------------------------------------------------
# Workload generators
# ---------------------------------------------------------------------------


def test_zipf_population_is_rank_skewed_and_deterministic():
    pop = ZipfPopulation(100, alpha=1.1)
    rng = random.Random(3)
    draws = [pop.sample(rng) for _ in range(4000)]
    counts = [draws.count(r) for r in range(4)]
    # rank 0 dominates and the head is monotone non-increasing
    assert counts[0] > counts[1] > counts[3]
    assert counts[0] > 0.1 * len(draws)
    # same seed, same schedule
    pop2, rng2 = ZipfPopulation(100, alpha=1.1), random.Random(3)
    assert draws == [pop2.sample(rng2) for _ in range(4000)]


def test_open_loop_arrivals_replay_bit_identical():
    def schedule(seed):
        src = OpenLoopSource(
            300.0, ZipfPopulation(500, 1.1), PayloadSizes("bimodal")
        )
        rng = random.Random(seed)
        return [src.arrivals(rng, e) for e in range(3)]

    a, b = schedule(9), schedule(9)
    assert a == b  # times, clients, seqs, payloads — everything
    assert schedule(10) != a
    # times ascend within their epoch and stay inside it
    for e, wave in enumerate(a):
        times = [t for t, _ in wave]
        assert times == sorted(times)
        assert all(e <= t < e + 1 for t in times)
    # chunked Poisson stays calibrated at rates past the exp() underflow
    # guard (rate 300 > the 500-chunk is exercised via rate 1200 below)
    big = OpenLoopSource(1200.0, ZipfPopulation(10, 1.0))
    n = len(big.arrivals(random.Random(0), 0))
    assert 900 < n < 1500  # ±~9 sigma around the mean


def test_closed_loop_tops_up_and_honors_backpressure():
    src = ClosedLoopSource(10, ZipfPopulation(50, 1.0))
    rng = random.Random(1)
    wave = src.arrivals(rng, 0)
    assert len(wave) == 10 and src.in_flight == 10
    assert src.arrivals(rng, 1) == []  # nothing committed yet
    src.on_committed(4)
    assert len(src.arrivals(rng, 2)) == 4
    assert src.arrivals(rng, 3, backpressure=True) == []  # deferred


# ---------------------------------------------------------------------------
# TransactionQueue: sampling cost, distribution, removal accounting
# ---------------------------------------------------------------------------


class _CountingRng(random.Random):
    """random.Random that counts entropy draws (cost proxy)."""

    calls = 0

    def randrange(self, *a, **kw):  # noqa: D102
        type(self).calls += 1
        return super().randrange(*a, **kw)


def test_choose_cost_is_batch_sized_not_mempool_sized():
    q = TransactionQueue(("tx", i) for i in range(10_000))
    rng = _CountingRng(5)
    _CountingRng.calls = 0
    sample = q.choose(rng, 10)
    assert len(sample) == 10 and len(set(sample)) == 10
    # rejection sampling touches ~amount slots, not the 10k mempool
    assert _CountingRng.calls < 100


def test_choose_distribution_uniform_and_seeded():
    q = TransactionQueue(("tx", i) for i in range(20))
    counts = {i: 0 for i in range(20)}
    rng = random.Random(7)
    trials = 2000
    for _ in range(trials):
        for _, i in q.choose(rng, 5):
            counts[i] += 1
    expect = trials * 5 / 20  # 500
    for i, c in sorted(counts.items()):
        assert abs(c - expect) < 0.2 * expect, (i, c)
    # replay determinism
    a = TransactionQueue(("tx", i) for i in range(20)).choose(random.Random(3), 5)
    b = TransactionQueue(("tx", i) for i in range(20)).choose(random.Random(3), 5)
    assert a == b


def test_choose_skips_tombstones_and_survives_churn():
    q = TransactionQueue(("tx", i) for i in range(100))
    q.remove_multiple([("tx", i) for i in range(0, 100, 2)])
    rng = random.Random(11)
    for _ in range(20):
        sample = q.choose(rng, 8)
        assert len(sample) == 8
        assert all(i % 2 == 1 for _, i in sample)  # only live entries
    # re-push of a removed tx must not double its sampling weight
    q.push(("tx", 0))
    hits = sum(
        ("tx", 0) in q.choose(rng, 10) for _ in range(2000)
    )
    expect = 2000 * 10 / len(q)
    assert abs(hits - expect) < 0.25 * expect


def test_remove_multiple_accounts_absent_entries():
    q = TransactionQueue([("tx", 1), ("tx", 2)])
    acct = q.remove_multiple([("tx", 1), ("tx", 99)])
    assert acct == RemovalAccount(removed=1, absent=1)
    assert acct.merged(RemovalAccount(2, 3)) == RemovalAccount(3, 4)
    assert len(q) == 1


def test_pop_oldest_is_fifo_over_live_entries():
    q = TransactionQueue([("tx", i) for i in range(4)])
    q.remove_multiple([("tx", 0), ("tx", 1)])
    assert q.pop_oldest() == ("tx", 2)
    assert q.pop_oldest() == ("tx", 3)
    assert q.pop_oldest() is None


def test_repush_behind_pop_cursor_relocates_to_tail():
    # a re-pushed tx whose tombstone sits BEHIND the pop_oldest cursor
    # must relocate to the tail, not revive in place where the cursor
    # would never see it (pre-fix: pop_oldest -> None on a 1-entry queue)
    q = TransactionQueue([("tx", "a"), ("tx", "b")])
    assert q.pop_oldest() == ("tx", "a")
    q.push(("tx", "a"))  # tombstone at slot 0, behind the cursor
    q.remove_multiple([("tx", "b")])
    assert q.pop_oldest() == ("tx", "a")
    assert q.pop_oldest() is None and len(q) == 0
    # ...and FIFO holds across the relocation: the re-push is NEW load
    q2 = TransactionQueue([("tx", "a"), ("tx", "b"), ("tx", "c")])
    q2.pop_oldest()  # drops a
    q2.push(("tx", "a"))  # re-push: now ordered b, c, a
    assert [q2.pop_oldest() for _ in range(3)] == [
        ("tx", "b"), ("tx", "c"), ("tx", "a")
    ]


def test_evict_oldest_capacity_bound_survives_resubmits():
    # fuzz the evict_oldest mempool with resubmits of committed/evicted
    # txs: depth must never exceed capacity and every eviction must have
    # had a real victim (pre-fix: a revived tombstone hid a live entry
    # from pop_oldest and depth reached capacity+1)
    rng = random.Random(711)
    mp = BoundedMempool(3, policy="evict_oldest")
    universe = [make_tx(0, i, b"p") for i in range(6)]
    for _ in range(400):
        if rng.random() < 0.7:
            out = mp.submit(rng.choice(universe))
            if out == "evicted_oldest":
                assert mp.last_evicted is not None
        else:
            mp.remove_committed(rng.sample(universe, rng.randrange(1, 3)))
        assert mp.depth <= 3


# ---------------------------------------------------------------------------
# BoundedMempool
# ---------------------------------------------------------------------------


def test_mempool_admission_outcomes_and_bounds():
    mp = BoundedMempool(capacity=4, policy="reject")
    txs = [make_tx(0, i, b"x" * 8) for i in range(6)]
    assert [mp.submit(t) for t in txs[:4]] == ["accepted"] * 4
    assert mp.submit(txs[0]) == "duplicate"
    assert mp.submit(txs[4]) == "dropped"  # full, reject policy
    assert mp.submit(("junk",)) == "invalid"
    assert mp.submit(make_tx(0, 9, b"x" * (1 << 17))) == "invalid"  # oversized
    assert mp.depth == 4 and mp.peak_depth == 4
    assert mp.dropped == 1 and mp.duplicates == 1 and mp.invalid == 2


def test_mempool_evict_oldest_policy_keeps_bound():
    mp = BoundedMempool(capacity=3, policy="evict_oldest")
    txs = [make_tx(1, i, b"p") for i in range(5)]
    for t in txs[:3]:
        assert mp.submit(t) == "accepted"
    assert mp.submit(txs[3]) == "evicted_oldest"
    assert mp.depth == 3 and mp.evicted == 1
    assert txs[0] not in mp and txs[3] in mp


def test_mempool_backpressure_hysteresis():
    mp = BoundedMempool(capacity=10, hi_frac=0.9, lo_frac=0.5)
    txs = [make_tx(2, i, b"p") for i in range(10)]
    for t in txs[:8]:
        mp.submit(t)
    assert not mp.backpressure
    mp.submit(txs[8])  # depth 9 >= hi
    assert mp.backpressure
    mp.remove_committed(txs[:3])  # depth 6 > lo: still on
    assert mp.backpressure
    mp.remove_committed(txs[3:5])  # depth 4 <= lo: clears
    assert not mp.backpressure


# ---------------------------------------------------------------------------
# TxTracker
# ---------------------------------------------------------------------------


def test_tracker_lifecycle_latency_and_dedup():
    tr = TxTracker()
    a, b = make_tx(0, 0, b"a"), make_tx(0, 1, b"b")
    tr.on_submit(a, 0.25)
    tr.on_submit(b, 0.5)
    tr.on_sampled([a, b], 1.0)
    assert tr.on_committed([a, b, a], 2.0) == 2  # cross-proposer dup
    assert tr.committed == 2 and tr.committed_duplicates == 1
    lat = tr.latency_summary()
    assert lat["count"] == 2 and 1.0 < lat["p50"] <= 2.0
    # unseen commit is accounted, not crashed on
    assert tr.on_committed([make_tx(9, 9, b"z")], 3.0) == 1
    assert tr.committed_unseen == 1


# ---------------------------------------------------------------------------
# Array driver: engine hooks, replay determinism, overload
# ---------------------------------------------------------------------------


def _array_driver(seed=7, rate=120.0, cap=4096, epochs=3, n=4, batch=16):
    net = ArrayHoneyBadgerNet(range(n), backend=MockBackend(), seed=1)
    src = OpenLoopSource(rate, ZipfPopulation(300, 1.1), PayloadSizes("fixed", 24))
    drv = ArrayTrafficDriver(
        net, src, random.Random(seed), batch_size=batch, mempool_capacity=cap
    )
    digests = []

    def digest_listener(batches):
        batch = batches[net.ids[0]]
        h = hashlib.sha256()
        for p in net.ids:
            h.update(bytes(batch.contributions[p]))
        digests.append(h.hexdigest())

    net.batch_listeners.append(digest_listener)
    rep = drv.run(epochs)
    return drv, rep, digests


def test_array_driver_commits_exactly_once_and_fans_out():
    drv, rep, digests = _array_driver()
    assert rep["committed"] > 0
    assert len(digests) == rep["epochs"] == 3  # extra listener fired per epoch
    t = drv.tracker
    assert t.committed == sum(rep["committed_per_epoch"])
    # every committed tx left every mempool: what remains is ≤ the
    # tracker's pending (not-yet-committed) set
    assert all(mp.depth <= t.pending for mp in drv.mempools)


def test_array_driver_seeded_replay_bit_identical():
    a_drv, a_rep, a_dig = _array_driver(seed=21)
    b_drv, b_rep, b_dig = _array_driver(seed=21)
    assert a_dig == b_dig  # identical Batches
    assert a_rep["committed_per_epoch"] == b_rep["committed_per_epoch"]
    assert a_drv.tracker.fingerprint() == b_drv.tracker.fingerprint()
    c_drv, _, c_dig = _array_driver(seed=22)
    assert c_dig != a_dig


def test_run_epochs_contribution_source_hook():
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=2)
    src = ClosedLoopSource(24, ZipfPopulation(50, 1.0))
    drv = ArrayTrafficDriver(
        net, src, random.Random(4), batch_size=8, mempool_capacity=256
    )
    net.run_epochs(2)  # the ENGINE loop sources contributions from traffic
    assert drv.epochs_run == 2
    assert drv.tracker.committed > 0


def test_checkpoint_detaches_traffic_hooks():
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=2)
    src = ClosedLoopSource(8, ZipfPopulation(10, 1.0))
    ArrayTrafficDriver(net, src, random.Random(0), batch_size=4)
    blob = net.checkpoint()  # live callables must not poison the snapshot
    assert net.batch_listeners and net.contribution_source is not None
    restored = ArrayHoneyBadgerNet.restore(blob, MockBackend())
    assert restored.batch_listeners == () and restored.contribution_source is None


def test_overload_backpressures_bounded_and_named():
    # arrival rate ~4x the commit plateau, tiny capacity
    sat_drv, sat_rep, _ = _array_driver(seed=5, rate=60.0, cap=4096, epochs=4)
    over_drv, over_rep, _ = _array_driver(seed=5, rate=400.0, cap=96, epochs=4)
    # memory stays bounded at capacity
    assert over_rep["mempool_peak_depth"] <= 96
    assert over_rep["mempool_dropped"] > 0
    # ...and so does the tracker: admission-rejected txs release their
    # pending entries instead of leaking linearly in offered load
    assert over_drv.tracker.pending <= sum(mp.depth for mp in over_drv.mempools)
    # committed throughput holds ~the saturation plateau (last epochs,
    # past warm-up)
    sat_tail = sat_rep["committed_per_epoch"][-1]
    over_tail = over_rep["committed_per_epoch"][-1]
    assert over_tail >= 0.9 * sat_tail
    # the stall reporter names the saturated source
    assert over_rep["status"]["state"] == "saturated"

    class _Stub:
        nodes = {}
        traffic = over_drv

    report = why_stalled(_Stub())
    assert report["traffic"]["state"] == "saturated"
    assert any("saturated" in s for s in report["summary"])


def test_saturated_is_recent_not_sticky():
    # an early overload burst must not pin "saturated" forever: once the
    # source dries up and everything drains, the state reads starved
    class _BurstThenDry(OpenLoopSource):
        def arrivals(self, rng, epoch, backpressure=False):
            self.rate = 400.0 if epoch == 0 else 0.0
            return super().arrivals(rng, epoch, backpressure=backpressure)

    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=1)
    src = _BurstThenDry(400.0, ZipfPopulation(100, 1.0), PayloadSizes("fixed", 16))
    drv = ArrayTrafficDriver(
        net, src, random.Random(9), batch_size=32, mempool_capacity=64,
    )
    rep = drv.run(8)
    assert rep["mempool_dropped"] > 0  # the burst really shed load
    assert drv.max_depth == 0 and drv.tracker.pending == 0  # fully drained
    assert drv.status()["state"] == "starved"


def test_closed_loop_slots_released_on_rejection():
    # concurrency >> capacity: rejected submissions must release their
    # in-flight slots or the source stops generating forever
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=1)
    src = ClosedLoopSource(20, ZipfPopulation(50, 1.0))
    drv = ArrayTrafficDriver(
        net, src, random.Random(3), batch_size=4, mempool_capacity=8,
    )
    drv.run(4)
    assert drv.tracker.dropped > 0
    # the window recovered its dropped slots: later waves kept generating
    # and the system kept committing
    assert src.in_flight <= src.concurrency
    assert drv.committed_per_epoch[-1] > 0


def test_evict_policy_releases_tracker_lifecycles():
    # fanout="one" + evict_oldest: an evicted tx is gone from EVERY
    # mempool, so its pending lifecycle must be released too
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=1)
    src = OpenLoopSource(300.0, ZipfPopulation(200, 1.1), PayloadSizes("fixed", 16))
    drv = ArrayTrafficDriver(
        net, src, random.Random(6), batch_size=8, mempool_capacity=32,
        mempool_policy="evict_oldest", fanout="one",
    )
    rep = drv.run(4)
    assert rep["mempool_evicted"] > 0
    assert rep["mempool_peak_depth"] <= 32
    assert drv.tracker.pending <= sum(mp.depth for mp in drv.mempools)


def test_evict_release_deduped_across_clone_mempools():
    # fanout="all" keeps the N mempools in lockstep, so every eviction
    # has the SAME victim in all of them: the closed-loop window must be
    # released once per unique victim, not N× (which would degenerate
    # fixed concurrency into an open loop)
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=1)
    src = ClosedLoopSource(20, ZipfPopulation(50, 1.0))
    drv = ArrayTrafficDriver(
        net, src, random.Random(3), batch_size=4, mempool_capacity=8,
        mempool_policy="evict_oldest",
    )
    rep = drv.run(4)
    assert rep["mempool_evicted"] > 0  # the dedup path was exercised
    # exact window accounting: a slot is held iff its tx is still
    # pending (neither committed nor released by an eviction)
    assert src.in_flight == drv.tracker.pending


def test_heartbeat_carries_traffic_fields():
    beats = []
    health = HealthReporter(interval_s=0.0, sink=beats.append)
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=3)
    src = OpenLoopSource(40.0, ZipfPopulation(50, 1.0))
    drv = ArrayTrafficDriver(
        net, src, random.Random(1), batch_size=8,
        mempool_capacity=128, health=health,
    )
    drv.run(2)
    assert beats
    assert "mempool_depth" in beats[-1] and "tx_commit_p99" in beats[-1]
    assert beats[-1]["tx_committed"] == drv.tracker.committed


# ---------------------------------------------------------------------------
# Object-runtime parity (small N)
# ---------------------------------------------------------------------------


def _object_net(n=4, batch_size=3, seed=0):
    from hbbft_tpu.net.virtual_net import NetBuilder
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger

    return (
        NetBuilder(range(n))
        .num_faulty(1)
        .crank_limit(10_000_000)
        .using(
            lambda ni, be, rng: QueueingHoneyBadger(
                ni, be, rng=rng, batch_size=batch_size, session_id=b"traffic"
            )
        )
        .build(seed=seed)
    )


def test_object_driver_small_n_parity():
    net = _object_net()
    src = ClosedLoopSource(9, ZipfPopulation(30, 1.0))
    drv = ObjectTrafficDriver(
        net, src, random.Random(6), batch_size=3, mempool_capacity=64
    )
    rep = drv.run(3)
    t = drv.tracker
    # everything committed exactly once through the REAL QHB pipeline
    assert t.committed > 0 and rep["committed"] == t.committed
    assert t.committed + t.pending == t.submitted - t.dropped - t.invalid
    # QHB's own removal accounting observed committed-elsewhere samples
    qhb = net.nodes[0].algorithm
    assert qhb.removal_account.removed > 0
    # the sample_listener hook closed submit→sampled intervals, so the
    # queue-dwell histogram is populated in object mode too (array parity)
    ql = t.summary()["queue_latency"]
    assert ql["count"] > 0
    # identical committed order on all correct nodes (batch equality)
    outs = [node.outputs for node in net.correct_nodes()]
    assert all(len(o) == len(outs[0]) for o in outs)


def test_traffic_instrumented_nodes_stay_snapshotable():
    # the driver's sample_listener (a live bound method) and net.traffic
    # are environment, not state: save_node must drop them instead of
    # refusing the checkpoint, and restore falls back to the class None
    from hbbft_tpu.utils.snapshot import load_node, save_node

    net = _object_net()
    src = ClosedLoopSource(9, ZipfPopulation(30, 1.0))
    drv = ObjectTrafficDriver(
        net, src, random.Random(6), batch_size=3, mempool_capacity=64
    )
    drv.run(1)
    algo = net.nodes[0].algorithm
    assert algo.sample_listener is not None
    restored = load_node(save_node(algo), MockBackend())
    assert restored.sample_listener is None
    net2 = load_node(save_node(net), MockBackend())
    assert net2.traffic is None
    # a queue holding a relocated (_DeadSlot) order entry round-trips
    q = TransactionQueue([("tx", "a"), ("tx", "b")])
    q.pop_oldest()
    q.push(("tx", "a"))  # relocation writes the dead-slot sentinel
    q2 = load_node(save_node(q), MockBackend())
    assert [q2.pop_oldest() for _ in range(3)] == [
        ("tx", "b"), ("tx", "a"), None
    ]


def test_object_driver_starved_source_named_by_why_stalled():
    net = _object_net(seed=1)
    src = OpenLoopSource(0.0, ZipfPopulation(10, 1.0))  # no arrivals, ever
    drv = ObjectTrafficDriver(
        net, src, random.Random(2), batch_size=3, mempool_capacity=16,
        cranks_per_wave=10_000,
    )
    drv.run(1)  # quiesces without a batch; starvation is not an error
    assert drv.status()["state"] == "starved"
    report = why_stalled(net)
    assert report["traffic"]["state"] == "starved"
    assert any("starved" in s for s in report["summary"])


# ---------------------------------------------------------------------------
# trace_report --traffic regression gate
# ---------------------------------------------------------------------------


def _traffic_rows_doc(tx_per_s, p99):
    return {
        "meta": {},
        "rows": [
            {
                "metric": "qhb_traffic",
                "value": tx_per_s,
                "curve": [
                    {
                        "n": 16, "batch_size": 64, "rate_frac": 1.0,
                        "tx_per_s": tx_per_s, "latency_p99": p99,
                    }
                ],
            }
        ],
    }


def test_trace_report_traffic_diff_gates_both_axes(tmp_path):
    from tools.trace_report import diff_traffic, report_traffic

    old = tmp_path / "old.json"
    old.write_text(json.dumps(_traffic_rows_doc(1000.0, 2.0)))
    same = tmp_path / "same.json"
    same.write_text(json.dumps(_traffic_rows_doc(1010.0, 1.9)))
    assert report_traffic(str(old), str(same), 0.10) == 0

    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_traffic_rows_doc(850.0, 2.0)))  # tx/s -15%
    assert report_traffic(str(old), str(slow), 0.10) == 1
    lagged = tmp_path / "lagged.json"
    lagged.write_text(json.dumps(_traffic_rows_doc(1000.0, 2.4)))  # p99 +20%
    assert report_traffic(str(old), str(lagged), 0.10) == 1
    entries = diff_traffic(str(old), str(lagged), 0.10)
    assert entries[0]["p99_regression"] and not entries[0]["tx_regression"]


# ---------------------------------------------------------------------------
# Million-client scale-out (PR 12): batched sampling + sharded mempool
# ---------------------------------------------------------------------------


class _DrawCountingRng(random.Random):
    """Counts python-level entropy calls (the per-wave cost contract)."""

    def __init__(self, seed):
        super().__init__(seed)
        self.calls = 0

    def random(self):
        self.calls += 1
        return super().random()

    def getrandbits(self, k):
        self.calls += 1
        return super().getrandbits(k)


def test_sample_wave_uses_constant_entropy_per_wave():
    pop = ZipfPopulation(100_000, 1.1)
    rng = _DrawCountingRng(5)
    wave = pop.sample_wave(rng, 4096)
    assert len(wave) == 4096
    assert all(isinstance(c, int) for c in wave[:10])
    # ONE seed draw keys the whole wave — no python-per-tx rng loop
    assert rng.calls == 1


def test_sample_wave_matches_scalar_quantile_math():
    # the scalar and wave paths share _locate: identical uniforms must
    # land identical ranks
    import numpy as np

    pop = ZipfPopulation(10_000, 1.1)

    class _Stub:
        def __init__(self, u):
            self.u = u

        def random(self):
            return self.u

    for u in (0.0, 0.1, 0.37, 0.5, 0.9, 0.999999):
        scalar = pop.sample(_Stub(u))
        wave = int(pop._locate(np.array([u * pop._total]))[0])
        assert scalar == wave


def test_sample_wave_distribution_matches_scalar_path():
    pop = ZipfPopulation(1_000, 1.1)
    rng = random.Random(3)
    scalar = [pop.sample(rng) for _ in range(20_000)]
    wave = pop.sample_wave(random.Random(4), 20_000)
    for rank in range(3):
        s = scalar.count(rank) / len(scalar)
        w = wave.count(rank) / len(wave)
        assert abs(s - w) < 0.25 * max(s, w), (rank, s, w)
    # replay determinism of the batched path
    assert pop.sample_wave(random.Random(4), 20_000) == wave


def test_sample_wave_cost_flat_from_1e4_to_1e6_clients():
    """The acceptance bound: per-wave host cost must not grow with the
    population (one vectorized searchsorted, O(k log C)); generous 8x
    slack absorbs CI noise on the shared 1-core box — the pre-vectorize
    per-tx bisect was >50x at this spread."""
    import time as _time

    pop4 = ZipfPopulation(10_000, 1.1)
    pop6 = ZipfPopulation(1_000_000, 1.1)
    rng = random.Random(1)

    def best_of(pop, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            pop.sample_wave(rng, 2048)
            best = min(best, _time.perf_counter() - t0)
        return best

    best_of(pop4, 2)  # warm numpy dispatch
    assert best_of(pop6) < 8 * best_of(pop4) + 1e-3


def test_payload_draw_wave_matches_kinds():
    rng = random.Random(2)
    assert PayloadSizes("fixed", size=40).draw_wave(rng, 5) == [40] * 5
    uni = PayloadSizes("uniform", lo=10, hi=20).draw_wave(rng, 500)
    assert all(10 <= s <= 20 for s in uni) and len(set(uni)) > 5
    bi = PayloadSizes("bimodal", small=8, large=256, heavy_frac=0.5)
    sizes = bi.draw_wave(rng, 400)
    assert set(sizes) == {8, 256}


def test_mempool_sharded_accounting_sums_and_status_shape():
    mp = BoundedMempool(500, shards=8)
    txs = [make_tx(i % 300, i // 300, b"x" * 8) for i in range(900)]
    for t in txs:
        mp.submit(t)
    mp.submit(txs[0])  # duplicate
    mp.submit(("junk",))  # invalid
    st = mp.status()
    # caller-visible keys unchanged from the unsharded pool
    for key in ("depth", "capacity", "policy", "backpressure", "accepted",
                "duplicates", "invalid", "dropped", "evicted", "peak_depth"):
        assert key in st
    shard_sts = mp.shard_status()
    assert len(shard_sts) == 8
    for field in ("accepted", "duplicates", "invalid", "dropped", "evicted"):
        assert sum(s[field] for s in shard_sts) == st[field], field
    assert sum(s["depth"] for s in shard_sts) == st["depth"] == 500
    # load actually spread over the digest keyspace
    assert sum(1 for s in shard_sts if s["accepted"] > 0) >= 6


def test_mempool_routing_is_deterministic_across_instances():
    a, b = BoundedMempool(100, shards=16), BoundedMempool(100, shards=16)
    for i in range(50):
        tx = make_tx(i, 0, b"p")
        assert a._route(tx) == b._route(tx)
        a.submit(tx)
        assert tx in a and tx not in b


def test_mempool_sharded_choose_uniform_and_capacity_bound():
    mp = BoundedMempool(400, shards=8)
    txs = [make_tx(i, 0, b"p") for i in range(400)]
    for t in txs:
        assert mp.submit(t) == "accepted"
    rng = random.Random(7)
    counts = {t: 0 for t in txs}
    trials = 1500
    for _ in range(trials):
        sample = mp.choose(rng, 20)
        assert len(sample) == 20 and len(set(sample)) == 20
        for t in sample:
            counts[t] += 1
    expect = trials * 20 / 400
    hot = [c for c in counts.values() if abs(c - expect) > 0.5 * expect]
    assert len(hot) < 0.02 * len(counts)  # ~uniform across the union
    # evict policy under shards keeps the GLOBAL bound
    ev = BoundedMempool(64, policy="evict_oldest", shards=4)
    for i in range(500):
        ev.submit(make_tx(i, 1, b"q"))
        assert ev.depth <= 64
    assert ev.evicted == 500 - 64


def test_mempool_sharded_remove_and_index_stay_bounded():
    # sustained submit/commit churn: per-shard tombstone indexes must
    # compact (memory ~O(live + recent), never O(total submitted))
    mp = BoundedMempool(1_000, shards=8)
    rng = random.Random(13)
    for round_ in range(40):
        batch = [make_tx(c, round_, b"r") for c in range(500)]
        for t in batch:
            mp.submit(t)
        committed = mp.choose(rng, 400)
        acct = mp.remove_committed(committed)
        assert acct.removed == 400
    index_slots = sum(len(sh.q._order) for sh in mp._shards)
    assert index_slots < 4 * mp.capacity
    assert mp.depth == mp.accepted - 400 * 40 - mp.evicted


def test_array_driver_sharded_replay_and_full_cell_cost_flat():
    """The acceptance criterion: a full bench-style cell over a
    10⁶-client population + sharded mempools runs with per-wave host
    cost flat vs the 10⁴-client shape (generous 5x bound — the work per
    wave is O(k log C) vectorized, not O(C) or python-per-tx)."""
    import time as _time

    def cell(clients, seed=5):
        net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=1)
        src = OpenLoopSource(
            200.0, ZipfPopulation(clients, 1.1), PayloadSizes("fixed", 16)
        )
        drv = ArrayTrafficDriver(
            net, src, random.Random(seed), batch_size=32,
            mempool_capacity=4096, mempool_shards=16,
        )
        t0 = _time.perf_counter()
        rep = drv.run(3)
        return rep, _time.perf_counter() - t0

    rep4, dt4 = cell(10_000)
    rep6, dt6 = cell(1_000_000)
    assert rep6["committed"] > 0
    assert dt6 < 5 * dt4 + 0.05
    # sharded mempools change nothing about replay determinism
    a, _ = cell(1_000_000, seed=9)
    b, _ = cell(1_000_000, seed=9)
    assert a["tracker"] == b["tracker"]
    assert a["committed_per_epoch"] == b["committed_per_epoch"]


def test_recent_window_idle_tail_reads_as_zeros():
    # review finding (PR 12): a fully idle tail must not freeze the
    # window at the last active slot — the controller would hold the
    # pre-idle demand forever and never step B down
    from hbbft_tpu.traffic import TxTracker

    tr = TxTracker()
    for e in range(4):
        for i in range(100):
            tr.on_submit(make_tx(i, e, b"p"), e + 0.5)
    busy = tr.recent_summary(4, now=4)
    assert busy["submitted_per_epoch"] == 100.0
    idle = tr.recent_summary(4, now=10)  # epochs 6..9 never happened
    assert idle["submitted_per_epoch"] == 0.0
    assert idle["submitted_last"] == 0.0
    half = tr.recent_summary(4, now=5)  # window 1..4: slot 4 is silent
    assert half["submitted_per_epoch"] == 75.0


def test_mempool_shard_count_bounded_and_prefix_covers_all():
    with pytest.raises(ValueError):
        BoundedMempool(10, shards=1 << 17)  # beyond the 4-byte... cap
    # every shard of a large pool is reachable through the 4-byte prefix
    mp = BoundedMempool(100_000, shards=64)
    for i in range(4_000):
        mp.submit(make_tx(i, 0, b"p"))
    assert all(s["depth"] > 0 for s in mp.shard_status())


def test_submit_digest_param_routes_identically():
    import hashlib as _hl

    from hbbft_tpu.utils import canonical as _canon

    mp = BoundedMempool(1_000, shards=16)
    for i in range(200):
        tx = make_tx(i, 0, b"p")
        d = _hl.sha256(_canon.encode(tx)).digest()
        assert mp._route(tx) == mp._route(tx, digest=d)
        mp.submit(tx, digest=d)
    # the precomputed-digest path stored them in the same shards the
    # hash-it-yourself path would read from
    for i in range(200):
        assert make_tx(i, 0, b"p") in mp
