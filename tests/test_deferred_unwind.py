"""Deferred-verify unwind under adversarial delivery (PR-5 seam hardening).

The PR-5 cross-round overlap lets verification resolve AFTER downstream
work was speculatively assembled; these tests pin the two unwind
contracts for a crafted-invalid share whose verdict arrives OUT OF ORDER
through MockBackend's simulated-async pipeline (``pipeline_chunk``
resolves chunks last-submitted-first through the real DispatchPipeline):

* protocol arm — the sender is FAULTED (``threshold_decrypt:
  invalid_share`` / ``threshold_sign:invalid_sig_share``), the share
  never reaches a combine, and every honest node still commits identical
  Batches; under every scheduler mode: ``random``, ``first``, and the
  new schedule layer.
* engine arm — ``ArrayHoneyBadgerNet`` must RAISE ``EngineInvariantError``
  before any Batch is emitted, in both hostpipe arms, even though the
  rejecting verdict resolves after the speculative combines.
"""

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.engine import ArrayHoneyBadgerNet, EngineInvariantError
from hbbft_tpu.net.adversary import CraftedShareAdversary
from hbbft_tpu.net.virtual_net import NetBuilder, NetSchedule
from hbbft_tpu.protocols.honey_badger import HoneyBadger


def _piped_mock(chunk=3):
    be = MockBackend()
    be.pipeline_chunk = chunk
    return be


# ---------------------------------------------------------------------------
# Protocol arm: VirtualNet + HoneyBadger + crafted shares
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["random", "first", "schedule"])
def test_crafted_share_faulted_out_of_order(mode):
    """A crafted-invalid dec share, verified through the simulated-async
    pipeline (chunks resolve out of order), still faults the sender on
    every honest node and never corrupts a Batch — under the random
    scheduler, the deterministic 'first' scheduler, and the new
    latency/jitter schedule layer."""
    backend = _piped_mock(chunk=3)
    builder = (
        NetBuilder(range(4))
        .num_faulty(1)
        .backend(backend)
        .adversary(CraftedShareAdversary(rate=1.0, kinds=("dec_share",)))
        .crank_limit(2_000_000)
        .using(lambda ni, be: HoneyBadger(ni, be, session_id=b"unwind"))
    )
    if mode == "schedule":
        builder = builder.schedule(NetSchedule(name="lan", latency=1, jitter=2))
    else:
        builder = builder.scheduler(mode)
    net = builder.build(seed=3)
    faulty = net.faulty_nodes()[0].id

    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    net.crank_until(
        lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
    )

    batches = {n.id: n.outputs[0] for n in net.correct_nodes()}
    ref = next(iter(batches.values()))
    assert all(b == ref for b in batches.values()), "batches diverged"
    # the crafted share was faulted, attributed ONLY to its sender.  (A
    # node whose ThresholdDecrypt already reached threshold+1 verified
    # shares terminates without verifying late shares — so not every
    # honest node necessarily observes the fault, but at least one must,
    # and nobody may accuse an honest node.)
    observed = [
        (node.id, f.node_id)
        for node in net.correct_nodes()
        for f in node.faults_observed
        if f.kind == "threshold_decrypt:invalid_share"
    ]
    assert observed, "no honest node ever faulted the crafted share"
    assert all(accused == faulty for _, accused in observed), observed
    assert not any(
        net.nodes[f.node_id].faulty is False
        for node in net.correct_nodes()
        for f in node.faults_observed
    ), "fault attributed to an honest node"
    # the pipeline really ran chunked (the out-of-order machinery engaged)
    assert backend.counters.dec_shares_verified > 0


def test_crafted_coin_share_faulted_through_pipeline():
    """Same contract for crafted COIN (sig) shares: the BA coin's
    ThresholdSign faults the sender through the chunked pipeline.  Mixed
    BA inputs force coin rounds so coin traffic actually flows."""
    from hbbft_tpu.protocols.binary_agreement import BinaryAgreement

    backend = _piped_mock(chunk=2)
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .backend(backend)
        .adversary(CraftedShareAdversary(rate=1.0, kinds=("sig_share",)))
        .crank_limit(2_000_000)
        .using(lambda ni, be: BinaryAgreement(ni, be, session_id=b"unwind-ba"))
        .build(seed=2)
    )
    faulty = {n.id for n in net.faulty_nodes()}
    for i in sorted(net.nodes):
        net.send_input(i, i % 2 == 0)
    net.crank_to_quiescence()
    decisions = {n.id: n.outputs for n in net.correct_nodes()}
    vals = {out[0] for out in decisions.values() if out}
    assert len(vals) == 1, f"divergent decisions {decisions}"
    observed = [
        (f.node_id, f.kind)
        for n in net.correct_nodes()
        for f in n.faults_observed
        if f.kind == "threshold_sign:invalid_sig_share"
    ]
    assert observed, "crafted coin share was never faulted"
    assert all(nid in faulty for nid, _ in observed), observed


# ---------------------------------------------------------------------------
# Engine arm: rejected share resolved out of order must raise pre-Batch
# ---------------------------------------------------------------------------


def _corrupt_one(items):
    """Swap the first item's share for another sender's share (the
    engine replicates each distinct item, so scan for a genuinely
    different share object)."""
    items = list(items)
    pk, ct, share = items[0]
    j = next(
        (j for j, (_p, _c, s) in enumerate(items) if s is not share), None
    )
    if j is not None:
        items[0] = (pk, ct, items[j][2])
    return items


class _CorruptingPipedBackend(MockBackend):
    """Simulated-async backend that corrupts ONE dec-share item per
    batch (swaps in another sender's share) so a real False verdict
    flows through the out-of-order chunk resolution."""

    pipeline_chunk = 3

    def verify_dec_shares_deferred(self, items):
        return super().verify_dec_shares_deferred(_corrupt_one(items))

    def verify_dec_shares(self, items):
        return super().verify_dec_shares(_corrupt_one(items))


@pytest.mark.parametrize("no_hostpipe", [False, True])
def test_engine_rejected_share_raises_before_batch(monkeypatch, no_hostpipe):
    if no_hostpipe:
        monkeypatch.setenv("HBBFT_TPU_NO_HOSTPIPE", "1")
    else:
        monkeypatch.delenv("HBBFT_TPU_NO_HOSTPIPE", raising=False)
    net = ArrayHoneyBadgerNet(range(4), backend=_CorruptingPipedBackend(), seed=1)
    contribs = {i: b"c%d" % i for i in net.ids}
    with pytest.raises(EngineInvariantError, match="decryption share"):
        net.run_epoch(contribs)
    # the unwind happened BEFORE emission: no epoch advanced, no report
    assert net.epoch == 0
    assert net.reports == []
