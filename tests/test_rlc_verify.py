"""Tests for grouped (random-linear-combination) batch verification.

The TpuBackend must return exactly the same per-item booleans as item-wise
verification — including when a group contains forged shares (fallback path
attributes faults precisely), across group sizes straddling the RLC
threshold and bucket-padding boundaries.
"""

import random

import pytest

from hbbft_tpu.ops.backend import TpuBackend


@pytest.fixture(scope="module")
def backend():
    return TpuBackend()


@pytest.fixture(scope="module")
def rng():
    return random.Random(77)


@pytest.fixture(scope="module")
def keyset(backend, rng):
    sks = backend.generate_key_set(2, rng)
    return sks, sks.public_keys()


def test_rlc_sig_groups_all_valid(backend, keyset):
    sks, pks = keyset
    items = []
    for doc in (b"coin-0", b"coin-1"):
        for i in range(5):
            share = sks.secret_key_share(i).sign_share(doc)
            items.append((pks.public_key_share(i), doc, share))
    assert backend.verify_sig_shares(items) == [True] * 10


def test_rlc_sig_group_with_forgery_attributes_exactly(backend, keyset):
    sks, pks = keyset
    doc = b"coin-forged"
    items = []
    want = []
    for i in range(6):
        share = sks.secret_key_share(i).sign_share(doc)
        if i == 3:  # swap in a share signed by the wrong key share
            share = sks.secret_key_share(4).sign_share(doc)
            want.append(False)
        else:
            want.append(True)
        items.append((pks.public_key_share(i), doc, share))
    assert backend.verify_sig_shares(items) == want


def test_rlc_mixed_group_sizes(backend, keyset):
    """Groups under the RLC threshold ride the direct path; larger ones the
    grouped path; results interleave back in input order."""
    sks, pks = keyset
    items = []
    want = []
    # 2 items (direct), 4 items (grouped)
    for doc, count in ((b"tiny", 2), (b"grouped", 4)):
        for i in range(count):
            share = sks.secret_key_share(i).sign_share(doc)
            items.append((pks.public_key_share(i), doc, share))
            want.append(True)
    # one bad in the tiny group
    bad = sks.secret_key_share(0).sign_share(b"other")
    items.append((pks.public_key_share(1), b"tiny", bad))
    want.append(False)
    assert backend.verify_sig_shares(items) == want


def test_rlc_dec_shares(backend, keyset, rng):
    sks, pks = keyset
    msg = b"grouped decryption"
    ct = pks.encrypt(msg, rng)
    items = []
    want = []
    for i in range(5):
        share = sks.secret_key_share(i).decrypt_share_unchecked(ct)
        items.append((pks.public_key_share(i), ct, share))
        want.append(True)
    # forged: share from a different index against pk 5
    wrong = sks.secret_key_share(0).decrypt_share_unchecked(ct)
    items.append((pks.public_key_share(5), ct, wrong))
    want.append(False)
    assert backend.verify_dec_shares(items) == want


def test_rlc_bisection_attributes_exactly_with_log_pairings(backend, keyset):
    """A contaminated group is bisected — halves re-checked by RLC, only
    sub-rlc_min_group leaves get exact pairings — and attribution is still
    exact.  With 1 forgery in 16 shares the exact-check bill must be the
    leaf (≤4 items), not the whole group (the per-item fallback the
    round-2 verdict flagged as an adversarial-DoS amplifier)."""
    sks, pks = keyset
    doc = b"coin-bisect"
    items = []
    want = []
    bad_at = 9
    for i in range(16):
        share = sks.secret_key_share(i).sign_share(doc)
        if i == bad_at:
            share = sks.secret_key_share(i).sign_share(b"forged-doc")
        items.append((pks.public_key_share(i), doc, share))
        want.append(i != bad_at)
    p0 = backend.counters.pairing_checks
    r0 = backend.counters.rlc_groups
    assert backend.verify_sig_shares(items) == want
    exact_checks = backend.counters.pairing_checks - p0
    assert 0 < exact_checks <= 4, exact_checks  # leaf only, not all 16
    # bisection ran extra RLC rounds: 1 top + halves + quarters
    assert backend.counters.rlc_groups - r0 >= 4


def test_rlc_bisection_two_forgeries_opposite_halves(backend, keyset, rng):
    """Forgeries in both halves force parallel bisection paths; both must
    be attributed, everything else accepted (dec-share variant)."""
    sks, pks = keyset
    ct = pks.encrypt(b"bisect both halves", rng)
    items = []
    want = []
    bad = {2, 13}
    for i in range(16):
        share = sks.secret_key_share(i).decrypt_share_unchecked(ct)
        if i in bad:
            share = sks.secret_key_share(15 - i).decrypt_share_unchecked(ct)
        items.append((pks.public_key_share(i), ct, share))
        want.append(i not in bad)
    p0 = backend.counters.pairing_checks
    assert backend.verify_dec_shares(items) == want
    assert backend.counters.pairing_checks - p0 <= 8  # two leaves at most
