"""Tests for grouped (random-linear-combination) batch verification.

The TpuBackend must return exactly the same per-item booleans as item-wise
verification — including when a group contains forged shares (fallback path
attributes faults precisely), across group sizes straddling the RLC
threshold and bucket-padding boundaries.
"""

import random

import pytest

from hbbft_tpu.ops.backend import TpuBackend


@pytest.fixture(scope="module")
def backend():
    return TpuBackend()


@pytest.fixture(autouse=True)
def _reset_adaptive_window(backend):
    """Zero the contamination-observation window between tests: the
    module-scoped backend otherwise carries rejection observations from
    one test's forgeries into the next test's group sizing (the adaptive
    feature working as designed — but these structural tests each pin a
    specific fixed-group shape).  Tests that want a trained window set
    it explicitly."""
    backend._rlc_obs_items = 0.0
    backend._rlc_obs_rejects = 0.0


@pytest.fixture(scope="module")
def rng():
    return random.Random(77)


@pytest.fixture(scope="module")
def keyset(backend, rng):
    sks = backend.generate_key_set(2, rng)
    return sks, sks.public_keys()


def test_rlc_sig_groups_all_valid(backend, keyset):
    sks, pks = keyset
    items = []
    for doc in (b"coin-0", b"coin-1"):
        for i in range(5):
            share = sks.secret_key_share(i).sign_share(doc)
            items.append((pks.public_key_share(i), doc, share))
    assert backend.verify_sig_shares(items) == [True] * 10


def test_rlc_sig_group_with_forgery_attributes_exactly(backend, keyset):
    sks, pks = keyset
    doc = b"coin-forged"
    items = []
    want = []
    for i in range(6):
        share = sks.secret_key_share(i).sign_share(doc)
        if i == 3:  # swap in a share signed by the wrong key share
            share = sks.secret_key_share(4).sign_share(doc)
            want.append(False)
        else:
            want.append(True)
        items.append((pks.public_key_share(i), doc, share))
    assert backend.verify_sig_shares(items) == want


def test_rlc_mixed_group_sizes(backend, keyset):
    """Groups under the RLC threshold ride the direct path; larger ones the
    grouped path; results interleave back in input order."""
    sks, pks = keyset
    items = []
    want = []
    # 2 items (direct), 4 items (grouped)
    for doc, count in ((b"tiny", 2), (b"grouped", 4)):
        for i in range(count):
            share = sks.secret_key_share(i).sign_share(doc)
            items.append((pks.public_key_share(i), doc, share))
            want.append(True)
    # one bad in the tiny group
    bad = sks.secret_key_share(0).sign_share(b"other")
    items.append((pks.public_key_share(1), b"tiny", bad))
    want.append(False)
    assert backend.verify_sig_shares(items) == want


def test_rlc_dec_shares(backend, keyset, rng):
    sks, pks = keyset
    msg = b"grouped decryption"
    ct = pks.encrypt(msg, rng)
    items = []
    want = []
    for i in range(5):
        share = sks.secret_key_share(i).decrypt_share_unchecked(ct)
        items.append((pks.public_key_share(i), ct, share))
        want.append(True)
    # forged: share from a different index against pk 5
    wrong = sks.secret_key_share(0).decrypt_share_unchecked(ct)
    items.append((pks.public_key_share(5), ct, wrong))
    want.append(False)
    assert backend.verify_dec_shares(items) == want


def test_rlc_bisection_attributes_exactly_with_log_pairings(
    backend, keyset, monkeypatch
):
    """A contaminated group is bisected — halves re-checked by RLC, only
    sub-rlc_min_group leaves get exact pairings — and attribution is still
    exact.  With 1 forgery in 8 shares the exact-check bill must be the
    leaf (≤2 items), not the whole group (the per-item fallback the
    round-2 verdict flagged as an adversarial-DoS amplifier).

    Compile budget (PR 20): 8 items with rlc_min_group=2 walks the same
    three-level ladder (top + halves + quarters) the old 16-item shape
    did, but the quarter round's [2, 2] split pads back into the halves'
    (2, 4) bucket — so the test compiles no (1, 16) or (2, 8) graphs,
    saving ~100 s of XLA:CPU wall on the 1-core box."""
    monkeypatch.setattr(backend, "rlc_min_group", 2)
    sks, pks = keyset
    doc = b"coin-bisect"
    items = []
    want = []
    bad_at = 5
    for i in range(8):
        share = sks.secret_key_share(i).sign_share(doc)
        if i == bad_at:
            share = sks.secret_key_share(i).sign_share(b"forged-doc")
        items.append((pks.public_key_share(i), doc, share))
        want.append(i != bad_at)
    p0 = backend.counters.pairing_checks
    r0 = backend.counters.rlc_groups
    assert backend.verify_sig_shares(items) == want
    exact_checks = backend.counters.pairing_checks - p0
    assert 0 < exact_checks <= 2, exact_checks  # leaf only, not all 8
    # bisection ran extra RLC rounds: 1 top + halves + quarters
    assert backend.counters.rlc_groups - r0 >= 4


def test_rlc_bisection_two_forgeries_opposite_halves(backend, keyset, rng):
    """Forgeries in both halves force parallel bisection paths; both must
    be attributed, everything else accepted (dec-share variant).

    Compile budget (PR 20): 8 items instead of 16 — the (1, 8) top ride
    is the shape test_rlc_dec_shares already compiled and the (2, 4)
    halves are the only new graph, dropping the old (1, 16) + (2, 8) +
    (4, 4) compiles (~100 s on the 1-core box)."""
    sks, pks = keyset
    ct = pks.encrypt(b"bisect both halves", rng)
    items = []
    want = []
    bad = {1, 6}
    for i in range(8):
        share = sks.secret_key_share(i).decrypt_share_unchecked(ct)
        if i in bad:
            share = sks.secret_key_share(7 - i).decrypt_share_unchecked(ct)
        items.append((pks.public_key_share(i), ct, share))
        want.append(i not in bad)
    p0 = backend.counters.pairing_checks
    assert backend.verify_dec_shares(items) == want
    assert backend.counters.pairing_checks - p0 <= 8  # two leaves at most


# ---------------------------------------------------------------------------
# Contamination-adaptive group sizing (blst's playbook; the r01 2×-at-1.6%
# cliff).  Fresh backends per test: the jitted group checks are process-
# global LRU caches, so no new compiles for shapes the tests above built.
# ---------------------------------------------------------------------------


def test_adaptive_cap_formula(monkeypatch):
    monkeypatch.delenv("HBBFT_TPU_NO_ADAPTIVE_RLC", raising=False)
    b = TpuBackend.__new__(TpuBackend)  # no __init__: pure-logic surface
    b._rlc_obs_items = 0.0
    b._rlc_obs_rejects = 0.0
    assert b._rlc_adaptive_cap() is None  # no observations: unlimited
    b._rlc_obs_items, b._rlc_obs_rejects = 100.0, 0.3
    assert b._rlc_adaptive_cap() is None  # 0.3% < rlc_adapt_min_rate
    b._rlc_obs_rejects = 1.6
    assert b._rlc_adaptive_cap() == 44  # k* = 0.7/c at the r01 cliff rate
    b._rlc_obs_rejects = 5.0
    assert b._rlc_adaptive_cap() == 14
    b._rlc_obs_rejects = 15.0
    assert b._rlc_adaptive_cap() == 5
    b._rlc_obs_rejects = 50.0
    assert b._rlc_adaptive_cap() == TpuBackend.rlc_min_group  # floor
    monkeypatch.setenv("HBBFT_TPU_NO_ADAPTIVE_RLC", "1")
    assert b._rlc_adaptive_cap() is None  # kill switch


def test_adaptive_split_rebalances_short_tails(monkeypatch):
    monkeypatch.delenv("HBBFT_TPU_NO_ADAPTIVE_RLC", raising=False)
    from hbbft_tpu.utils.metrics import Counters

    b = TpuBackend.__new__(TpuBackend)
    b.counters = Counters()
    b._rlc_obs_items, b._rlc_obs_rejects = 100.0, 17.5  # cap = 4
    assert b._rlc_adaptive_cap() == 4
    out = b._rlc_apply_cap([list(range(10)), list(range(10, 13))])
    # 10 → 4 + 4 + tail 2 (< min group) rebalanced into the prior slice;
    # 3 ≤ cap stays whole; indices preserved exactly
    assert [len(g) for g in out] == [4, 6, 3]
    assert sorted(i for g in out for i in g) == list(range(13))
    assert b.counters.rlc_adaptive_splits == 1
    # no observations → structure untouched (the honest-path identity)
    b._rlc_obs_items = b._rlc_obs_rejects = 0.0
    groups = [list(range(16))]
    assert b._rlc_apply_cap(groups) is groups


def test_adaptive_split_results_and_attribution_identical(keyset):
    """With a trained contamination window the next batch runs in split
    groups — same verdicts, same exact attribution, splits counted."""
    sks, pks = keyset
    doc = b"adaptive-split"
    items = []
    for i in range(6):
        share = sks.secret_key_share(i).sign_share(doc)
        items.append((pks.public_key_share(i), doc, share))
    fresh = TpuBackend()
    fresh._rlc_obs_items, fresh._rlc_obs_rejects = 100.0, 25.0  # cap = 3
    assert fresh.verify_sig_shares(items) == [True] * 6
    assert fresh.counters.rlc_adaptive_splits == 1
    assert fresh.counters.rlc_groups == 2  # 6 → [3, 3]
    # honest batch re-grows the window: rate decays toward zero
    assert fresh._rlc_observed_rate() < 0.25


def test_adaptive_honest_path_identical_to_kill_switch(keyset, monkeypatch):
    """At zero observed contamination the adaptive arm's group structure,
    dispatch count, and results are IDENTICAL to the fixed arm."""
    sks, pks = keyset
    doc = b"adaptive-honest"
    items = []
    for i in range(6):
        share = sks.secret_key_share(i).sign_share(doc)
        items.append((pks.public_key_share(i), doc, share))
    runs = {}
    for arm, kill in (("adaptive", "0"), ("fixed", "1")):
        monkeypatch.setenv("HBBFT_TPU_NO_ADAPTIVE_RLC", kill)
        b = TpuBackend()
        out = [b.verify_sig_shares(items), b.verify_sig_shares(items)]
        runs[arm] = (
            out,
            b.counters.rlc_groups,
            b.counters.device_dispatches,
            b.counters.rlc_adaptive_splits,
        )
    monkeypatch.delenv("HBBFT_TPU_NO_ADAPTIVE_RLC", raising=False)
    assert runs["adaptive"] == runs["fixed"]
    assert runs["adaptive"][3] == 0  # no splits on honest traffic


@pytest.mark.slow
def test_adaptive_beats_fixed_under_contamination(monkeypatch):
    """At ≥5% contamination the trained adaptive arm does strictly less
    group-ladder work and fewer dispatches than fixed whole-document
    groups, with identical exact attribution (the deterministic core of
    the adv_matrix bench acceptance).  The contaminated batch is the
    bench's own construction, imported so the test and the adv_matrix
    row can never silently measure different workloads."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import _adv_contaminated_items

    stats = {}
    for arm, kill in (("adaptive", "0"), ("fixed", "1")):
        monkeypatch.setenv("HBBFT_TPU_NO_ADAPTIVE_RLC", kill)
        b = TpuBackend()
        items, want = _adv_contaminated_items(b, gct=2, k=32, frac=0.05)
        assert b.verify_dec_shares(items) == want  # warm + train
        lf0 = b.counters.ladder_field_muls
        d0 = b.counters.device_dispatches
        assert b.verify_dec_shares(items) == want
        stats[arm] = (
            b.counters.ladder_field_muls - lf0,
            b.counters.device_dispatches - d0,
        )
    monkeypatch.delenv("HBBFT_TPU_NO_ADAPTIVE_RLC", raising=False)
    assert stats["adaptive"][0] < stats["fixed"][0], stats
    assert stats["adaptive"][1] <= stats["fixed"][1], stats
