"""Resume behavior of the TPU-window runbook (round-4 verdict Weak #4).

Round 4's window died after step 3 of 9; on the next alive transition the
watcher restarted from step 1 and re-measured already-recorded steps while
the north star waited.  The round-5 runbook content-checks each step's
snapshot and skips verified ones, so a resumed window leads with the top
uncaptured item.  These tests drive `--list` (no TPU, runs nothing) against
a temp artifact dir simulating a killed window.
"""

import json
import os
import subprocess

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "tools", "tpu_window.sh")

_ALL_STEPS = [
    "n100", "matrix_rns_a", "matrix_limb_a", "matrix_rns_b", "matrix_limb_b",
    "glv_ab", "host_ab", "adv_matrix", "qhb_traffic", "slo_traffic",
    "crash_matrix", "mesh_scaling", "n16_churn", "flips10k", "kernel_levers",
    "driver_budget", "rs_ab", "rs_plane", "fused_chain", "n32_churn",
    "n64coin", "n100_churn",
]


def _run_list(art_dir):
    proc = subprocess.run(
        ["bash", _SCRIPT, "--list"],
        env={**os.environ, "TPU_WINDOW_ART": str(art_dir)},
        capture_output=True,
        text=True,
        cwd=_REPO,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    pending = [l.split("pending: ", 1)[1] for l in proc.stdout.splitlines()
               if l.startswith("pending: ")]
    skipped = [l.split(" skip ")[1].split(" ")[0] for l in proc.stdout.splitlines()
               if " skip " in l]
    return pending, skipped


def _write_snapshot(art_dir, step, rows):
    (art_dir / f"rows_after_{step}.json").write_text(
        json.dumps({"meta": {}, "rows": rows})
    )


def test_fresh_window_runs_everything_north_star_first(tmp_path):
    pending, skipped = _run_list(tmp_path)
    assert pending == _ALL_STEPS
    assert not skipped


def test_completed_steps_skip_and_priority_resumes(tmp_path):
    _write_snapshot(tmp_path, "n100", [{
        "metric": "array_epochs_per_sec_n100", "value": 0.1,
        "backend": "TpuBackend", "epochs": 10,
    }])
    _write_snapshot(tmp_path, "matrix_rns_a", [{
        "metric": "rlc_dec_verify_throughput", "value": 16789.0,
        "fq_impl": "rns",
    }])
    pending, skipped = _run_list(tmp_path)
    assert skipped == ["n100", "matrix_rns_a"]
    assert pending[0] == "matrix_limb_a"  # top UNCAPTURED item leads


def test_crashed_step_snapshot_without_row_reruns(tmp_path):
    # a step killed mid-run leaves a snapshot missing its row (or with the
    # wrong backend/impl): content check must force a re-run
    _write_snapshot(tmp_path, "n100", [{
        "metric": "array_epochs_per_sec_n100", "value": 2.3,
        "backend": "MockBackend",  # wrong backend — not the north star
    }])
    _write_snapshot(tmp_path, "matrix_limb_a", [{
        "metric": "rlc_dec_verify_throughput",  # no "value": errored row
        "error": "killed",
        "fq_impl": "limb",
    }])
    pending, _ = _run_list(tmp_path)
    assert "n100" in pending and "matrix_limb_a" in pending
