"""Budget-mode guarantees for the driver bench (round-4 verdict Weak #3).

Round 4's driver run was timeout-killed (rc=124) before the support-first
row order reached a single flagship row, so BENCH_r04.json carried none of
them.  Two properties must hold from round 5 on:

* plan: under a BENCH_BUDGET the row order is FLAGSHIP-FIRST and the
  real-crypto N=100 row is part of a TPU driver run's plan;
* kill-safety: a budget run that dies (or skips everything) still leaves a
  self-describing BENCH_rows.json — skipped benches emit labeled rows
  rather than vanishing.

The bench module is loaded by file path (repo root is not a package).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(_REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_budget_plan_is_flagship_first(bench):
    names = [n for n, _ in bench._plan_benches(None, "tpu", 3000.0)]
    assert names[0] == "rlc_dec"
    flag = ["rlc_dec", "share_verify", "rlc_sig", "g2_sign", "coin_e2e",
            "rlc_dec_adversarial", "adv_matrix", "array_n16_tpu",
            "array_n100_tpu"]
    assert names[: len(flag)] == flag
    # every flagship row comes before every support/mock row
    assert names.index("array_n100_tpu") < names.index("rs_encode")
    assert names[-1] == "array_n100"  # mock macro last


def test_legacy_plan_unchanged_without_budget(bench):
    names = [n for n, _ in bench._plan_benches(None, "tpu", 0.0)]
    assert names[0] == "rs_encode" and names[-1] == "array_n100"
    assert "array_n100_tpu" not in names  # round-1..4 row set preserved
    assert names.index("rlc_dec") > names.index("rlc_sig")


def test_qhb_traffic_planned_both_modes(bench):
    """The traffic curve row is part of both orderings (it is the only
    row measuring sustained tx/s + commit latency), sits after the
    flagship crypto prefix under a budget, and carries a cost estimate."""
    for budget in (0.0, 3000.0):
        names = [n for n, _ in bench._plan_benches(None, "tpu", budget)]
        assert "qhb_traffic" in names
    budgeted = [n for n, _ in bench._plan_benches(None, "tpu", 3000.0)]
    assert budgeted.index("qhb_traffic") < budgeted.index("rs_encode")
    assert "qhb_traffic" in bench._BENCH_EST_S


def test_slo_traffic_planned_both_modes(bench):
    """The control-plane row (adaptive vs fixed-B under the swing trace)
    rides both orderings right after the qhb_traffic curve, ahead of
    the support rows under a budget, with a cost estimate."""
    for budget in (0.0, 3000.0):
        names = [n for n, _ in bench._plan_benches(None, "tpu", budget)]
        assert "slo_traffic" in names
        assert names.index("qhb_traffic") < names.index("slo_traffic")
    budgeted = [n for n, _ in bench._plan_benches(None, "tpu", 3000.0)]
    assert budgeted.index("slo_traffic") < budgeted.index("rs_encode")
    assert "slo_traffic" in bench._BENCH_EST_S


def test_mesh_scaling_planned_both_modes(bench):
    """The per-device dispatcher structure row (PR 18) rides both
    orderings — cheap, so under a budget it runs ahead of the traffic
    curves and the support rows — with a cost estimate."""
    for budget in (0.0, 3000.0):
        names = [n for n, _ in bench._plan_benches(None, "tpu", budget)]
        assert "mesh_scaling" in names
    budgeted = [n for n, _ in bench._plan_benches(None, "tpu", 3000.0)]
    assert budgeted.index("mesh_scaling") < budgeted.index("qhb_traffic")
    assert budgeted.index("mesh_scaling") < budgeted.index("rs_encode")
    assert "mesh_scaling" in bench._BENCH_EST_S


def test_rs_plane_ab_planned_both_modes(bench):
    """The device erasure/hash plane A/B row (PR 19) rides both
    orderings next to the rs_encode/rs_host kernel rows — a support
    diagnostic, so under a budget it stays behind the flagship prefix —
    with a cost estimate."""
    for budget in (0.0, 3000.0):
        names = [n for n, _ in bench._plan_benches(None, "tpu", budget)]
        assert "rs_plane_ab" in names
        assert names.index("rs_host") < names.index("rs_plane_ab")
    budgeted = [n for n, _ in bench._plan_benches(None, "tpu", 3000.0)]
    assert budgeted.index("array_n100_tpu") < budgeted.index("rs_plane_ab")
    assert "rs_plane_ab" in bench._BENCH_EST_S


def test_fused_chain_ab_planned_both_modes(bench):
    """The VMEM-resident fused tower chain A/B row (PR 20) rides both
    orderings: a diagnostic, so it stays behind the flagship prefix
    under a budget — but directly behind it (ahead of glv_ladder and
    every support row), so a timeout-killed window still captures the
    device-chain A/B — with a cost estimate."""
    for budget in (0.0, 3000.0):
        names = [n for n, _ in bench._plan_benches(None, "tpu", budget)]
        assert "fused_chain_ab" in names
    budgeted = [n for n, _ in bench._plan_benches(None, "tpu", 3000.0)]
    assert budgeted.index("array_n100_tpu") < budgeted.index("fused_chain_ab")
    assert budgeted.index("fused_chain_ab") < budgeted.index("glv_ladder")
    assert budgeted.index("fused_chain_ab") < budgeted.index("rs_encode")
    assert "fused_chain_ab" in bench._BENCH_EST_S


def test_n100_tpu_gating(bench):
    # off-TPU driver runs never attempt the real-crypto N=100 row...
    assert "array_n100_tpu" not in [
        n for n, _ in bench._plan_benches(None, "cpu", 3000.0)
    ]
    # ...but an explicit BENCH_ONLY request is honored on any platform
    assert [n for n, _ in bench._plan_benches({"array_n100_tpu"}, "cpu", 0.0)] == [
        "array_n100_tpu"
    ]


def test_every_planned_bench_has_a_cost_estimate(bench):
    for plat in ("tpu", "cpu"):
        for budget in (0.0, 3000.0):
            for name, _ in bench._plan_benches(None, plat, budget):
                assert name in bench._BENCH_EST_S, name


def test_exhausted_budget_still_writes_labeled_rows(bench, tmp_path):
    """Simulated-kill path: with a 1-second budget every bench is skipped,
    yet the run exits 0 and BENCH_rows.json records one labeled row per
    planned bench plus the budget in meta (what a timeout-killed run's
    partial file looks like, minus whatever had already completed)."""
    rows_path = tmp_path / "rows.json"
    env = dict(os.environ)
    env.update(
        BENCH_BUDGET="1",
        BENCH_ROWS_PATH=str(rows_path),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        BENCH_PLATFORM_CHECKED="1",  # skip the accelerator probe
    )
    env.pop("BENCH_ONLY", None)
    # the ambient remote-TPU plugin attaches whenever this is set, and it
    # outranks JAX_PLATFORMS — the test must stay off the real chip
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        cwd=_REPO,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    data = json.loads(rows_path.read_text())
    assert data["meta"]["budget_seconds"] == 1.0
    assert data["rows"], "no rows written"
    skipped = [r for r in data["rows"] if "skipped" in r]
    assert skipped and all("budget exhausted" in r["skipped"] for r in skipped)
    planned = [n for n, _ in bench._plan_benches(None, "cpu", 1.0)]
    assert {r["metric"] for r in skipped} == set(planned)


def test_n100_tpu_adaptive_skip_when_budget_too_small(bench, tmp_path):
    """The adaptive-epoch branch must SKIP (with a labeled row) when not
    even one epoch fits the remaining budget — rather than launching a
    doomed run into the driver's timeout."""
    rows_path = tmp_path / "rows.json"
    env = dict(os.environ)
    env.update(
        BENCH_BUDGET="2000",
        BENCH_ONLY="array_n100_tpu",
        BENCH_N100_TPU_EPOCH_EST="10000",  # one epoch alone exceeds budget
        BENCH_ROWS_PATH=str(rows_path),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        BENCH_PLATFORM_CHECKED="1",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        env=env, capture_output=True, text=True, cwd=_REPO, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    data = json.loads(rows_path.read_text())
    (row,) = data["rows"]
    assert row["metric"] == "array_n100_tpu"
    assert "budget exhausted" in row["skipped"]
