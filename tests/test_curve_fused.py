"""Golden tests: fused scalar-mul ladder kernel vs the scan ladder.

Interpret mode on CPU; short bit widths keep in-kernel iteration counts
(and thus interpret cost) small — any scalar < 2^width is ladder-safe
(curve.scalars_to_bits).
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.ops import curve, curve_fused, pairing_fused


@pytest.fixture(scope="module", autouse=True)
def small_tile():
    # Interpret-mode cost scales with TILE (lanes are emulated in Python):
    # the real sublane width is 8, and 3-4 test lanes padded to TILE=128
    # made this module take ~18 min of CPU suite time.  TILE=8 keeps the
    # same kernel code paths at ~1/16 the emulation work.
    old = pairing_fused.TILE
    pairing_fused.TILE = 8
    curve_fused._ladder_call.cache_clear()
    yield
    pairing_fused.TILE = old
    curve_fused._ladder_call.cache_clear()


@pytest.fixture(scope="module")
def rng():
    return random.Random(13)


def _bits(rng, n, width):
    scalars = [rng.randrange(0, 1 << width) for _ in range(n)]
    scalars[-1] = 0  # exercise an all-zero ladder (stays at infinity)
    return scalars, jnp.asarray(curve.scalars_to_bits(scalars, width))


def test_g1_ladder_matches_golden(rng):
    width, n = 8, 4
    scalars, bits = _bits(rng, n, width)
    pts = [gold.G1_GEN] * (n - 1) + [None]  # include an infinite input
    P = curve.g1_to_device(pts)
    out = curve_fused.scalar_mul(1, bits, P, interpret=True)
    got = curve.g1_from_device(out)
    for i in range(n):
        if pts[i] is None or scalars[i] == 0:
            assert got[i] is None
        else:
            assert got[i] == gold.ec_mul(gold.FQ, scalars[i], pts[i])


def test_g2_ladder_matches_golden(rng):
    width, n = 8, 3
    scalars, bits = _bits(rng, n, width)
    pts = [gold.G2_GEN] * n
    P = curve.g2_to_device(pts)
    out = curve_fused.scalar_mul(2, bits, P, interpret=True)
    got = curve.g2_from_device(out)
    for i in range(n):
        if scalars[i] == 0:
            assert got[i] is None
        else:
            assert got[i] == gold.ec_mul(gold.FQ2, scalars[i], pts[i])


def test_g2_ladder_matches_scan_path(rng):
    """Fused kernel vs the lax.scan ladder on identical inputs."""
    width, n = 12, 3
    _, bits = _bits(rng, n, width)
    P = curve.g2_to_device([gold.G2_GEN] * n)
    want = curve.scalar_mul(curve._F2, bits, P)
    got = curve_fused.scalar_mul(2, bits, P, interpret=True)
    assert curve.g2_from_device(got) == curve.g2_from_device(want)


def test_fused_ladder_under_vmap(rng, monkeypatch):
    """The RLC verification graphs vmap linear_combine over groups; the
    fused ladder must produce identical combines under vmap batching."""
    width, G, K = 8, 2, 3
    scal = [[rng.randrange(1, 1 << width) for _ in range(K)] for _ in range(G)]
    bits = jnp.asarray(
        np.stack([curve.scalars_to_bits(r, width) for r in scal])
    )
    flat = curve.g2_to_device([gold.G2_GEN] * (G * K))
    P = jax.tree_util.tree_map(
        lambda c: jnp.asarray(c).reshape((G, K) + jnp.asarray(c).shape[1:]),
        flat,
    )
    zeros = jnp.zeros((G, K), dtype=bool)

    want = jax.vmap(curve.linear_combine_g2)(P, bits, zeros)

    monkeypatch.setattr(curve_fused, "_use", lambda: True)
    got = jax.vmap(curve.linear_combine_g2)(P, bits, zeros)

    for gi in range(G):
        take = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda c: np.asarray(c)[gi], t
        )
        assert curve.g2_from_device(take(got)) == curve.g2_from_device(
            take(want)
        )
