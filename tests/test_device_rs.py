"""Device erasure/hash plane (PR 19 tentpole).

Pins the three claims the plane rides on:

* parity — ``TpuBackend.rs_encode_batch`` / ``rs_reconstruct_batch`` /
  ``merkle_build_batch`` / ``merkle_verify_batch`` are bit-identical to
  the host codec + hashlib loops, fuzzed over random erasure patterns ×
  shard sizes × codec shapes (CPU JAX), error cases included;
* the bounded decode-matrix cache — capacity, LRU eviction order, and
  hit identity of :class:`~hbbft_tpu.ops.gf256.DecodeMatrixCache` (the
  erasure-pattern-keyed constant store both JaxRSCodec and the backend
  plane share);
* the fold — an N=16 engine A/B (device plane vs ``HBBFT_TPU_NO_DEVICE_RS=1``)
  producing bit-identical Batches and EpochReports while the device arm's
  RS/Merkle work reappears under ``device_seconds_rs_enc``/``_merkle``
  (and the kill-switch arm dispatches nothing).

The A/B vehicle is a mock-crypto backend that borrows the REAL device
plane from TpuBackend: full TpuBackend epochs need the BLS kernel
compiles, but the RS/Merkle jits are small on XLA:CPU.  Distinct SHA-256
entry-point shapes cost ~10 s of XLA:CPU compile each, so the tests below
deliberately reuse a handful of shapes.
"""

import dataclasses
import random

import numpy as np
import pytest

from hbbft_tpu.crypto.backend import CryptoBackend, MockBackend
from hbbft_tpu.crypto.erasure import RSCodec, gf256
from hbbft_tpu.crypto.merkle import MerkleTree, PackedProofs
from hbbft_tpu.engine import ArrayHoneyBadgerNet
from hbbft_tpu.ops.backend import TpuBackend
from hbbft_tpu.ops.gf256 import DecodeMatrixCache, JaxRSCodec, expand_gf_matrix
from hbbft_tpu.ops.pipeline import DispatchPipeline


@pytest.fixture(scope="module")
def tbe():
    return TpuBackend()


@pytest.fixture(autouse=True)
def _device_rs_on(monkeypatch):
    monkeypatch.delenv("HBBFT_TPU_NO_DEVICE_RS", raising=False)


# ---------------------------------------------------------------------------
# Satellite: the bounded decode-matrix cache
# ---------------------------------------------------------------------------


def test_decode_cache_capacity_and_lru_eviction():
    c = DecodeMatrixCache(capacity=2)
    p1 = ((0, 1, 2), (3,))
    p2 = ((0, 1, 3), (2,))
    p3 = ((0, 2, 3), (1,))
    c.get(*p1)
    c.get(*p2)
    assert len(c) == 2
    # touching p1 makes p2 the LRU victim for the next insert
    c.get(*p1)
    c.get(*p3)
    assert len(c) == 2, "capacity bound violated"
    assert list(c.keys()) == [p1, p3], "eviction is not least-recently-used"


def test_decode_cache_hit_returns_same_constant():
    c = DecodeMatrixCache(capacity=4)
    xs, missing = (0, 2, 4), (1, 3)
    first = c.get(xs, missing)
    assert c.get(list(xs), list(missing)) is first, (
        "a cache hit must reuse the placed device constant, not rebuild it"
    )
    want = expand_gf_matrix(gf256().lagrange_matrix(list(xs), list(missing)))
    assert np.array_equal(np.asarray(first), want)


def test_decode_cache_capacity_pins(tbe):
    """The bound is the contract: 64 patterns covers every stable
    crashed-set workload while keeping combinatorial pattern churn from
    growing device constants without limit."""
    assert JaxRSCodec._DECODE_CACHE_MAX == 64
    assert JaxRSCodec(3, 2)._decode_cache.capacity == 64
    assert tbe._rs_dec_cache.capacity == 64


# ---------------------------------------------------------------------------
# Satellite: parity fuzz — device RS vs host codec, bit for bit
# ---------------------------------------------------------------------------

_CODEC_SHAPES = [(3, 2), (6, 10), (4, 3)]  # N=16's k=6/m=10 in the middle
_BLOCK_LENS = (0, 1, 7, 17, 64)


def test_rs_encode_parity_fuzz(tbe):
    rng = random.Random(7)
    for k, m in _CODEC_SHAPES:
        codec = RSCodec(k, m)
        for _ in range(3):
            datas = [
                bytes(rng.randrange(256) for _ in range(rng.choice(_BLOCK_LENS)))
                for _ in range(rng.randrange(1, 6))
            ]
            want = [codec.encode(d) for d in datas]
            assert tbe.rs_encode_batch(codec, datas) == want


def test_rs_reconstruct_parity_fuzz(tbe):
    rng = random.Random(13)
    for k, m in _CODEC_SHAPES:
        codec = RSCodec(k, m)
        lists = []
        for blen in (24, 24, 7, 0, 24):  # repeats exercise pattern grouping
            shards = list(codec.encode(bytes(rng.randrange(256) for _ in range(blen))))
            for j in rng.sample(range(codec.n), rng.randrange(0, m + 1)):
                shards[j] = None  # ≤ m erasures, incl. the all-present case
            lists.append(shards)
        want = [codec.reconstruct(list(s)) for s in lists]
        assert tbe.rs_reconstruct_batch(codec, lists) == want


def test_rs_reconstruct_error_cases_match_host(tbe):
    codec = RSCodec(3, 2)
    enc = codec.encode(b"hello world!")
    # too few present shards: the exact host raise, in item order
    few = [None, None, None, enc[3], enc[4]]
    with pytest.raises(ValueError):
        codec.reconstruct(list(few))
    with pytest.raises(ValueError):
        tbe.rs_reconstruct_batch(codec, [few])
    # wrong slot count
    with pytest.raises(ValueError):
        tbe.rs_reconstruct_batch(codec, [enc[:4]])


# ---------------------------------------------------------------------------
# Device Merkle build + verify parity (one small shape + one padded shape)
# ---------------------------------------------------------------------------


def _shard_lists(rng, trees, n, leaf_len):
    return [
        [bytes(rng.randrange(256) for _ in range(leaf_len)) for _ in range(n)]
        for _ in range(trees)
    ]


def test_merkle_build_and_verify_parity(tbe):
    rng = random.Random(5)
    sls = _shard_lists(rng, trees=3, n=8, leaf_len=13)
    host = [MerkleTree(list(sl)) for sl in sls]
    dev = tbe.merkle_build_batch(sls)
    for h, d in zip(host, dev):
        assert d.levels == h.levels
        assert d.root_hash == h.root_hash
    packed = PackedProofs.from_trees(dev, 8, device=True)
    assert packed is not None
    want = packed.validate(1)
    assert want == [True] * len(packed)
    assert tbe.merkle_verify_batch(packed, reps=2) == want
    # corrupt one tree's root: exactly its n_leaves proofs flip, and the
    # device walk agrees with the host validator on every row (same
    # array shapes as above — no extra XLA compile)
    bad_roots = np.array(packed.roots, copy=True)
    bad_roots[8:16] ^= 1
    bad = PackedProofs(
        packed.leaves, packed.paths, packed.indices, bad_roots, packed.n_leaves
    )
    verdicts = tbe.merkle_verify_batch(bad)
    assert verdicts == bad.validate(1)
    assert verdicts == [True] * 8 + [False] * 8 + [True] * 8


def test_merkle_parity_non_power_of_two(tbe):
    """n=6 leaves: the device tree must pad with the same tagged empty
    leaf the host tree does."""
    rng = random.Random(6)
    sls = _shard_lists(rng, trees=2, n=6, leaf_len=13)
    host = [MerkleTree(list(sl)) for sl in sls]
    dev = tbe.merkle_build_batch(sls)
    for h, d in zip(host, dev):
        assert d.levels == h.levels


def test_merkle_build_falls_back_on_ragged_batches(tbe):
    """Non-rectangular batches (mixed leaf counts or lengths) take the
    host loop — same trees, no device dispatch."""
    sls = [[b"aa", b"bb", b"cc"], [b"dd", b"ee"]]
    before = tbe.counters.device_dispatches
    dev = tbe.merkle_build_batch(sls)
    assert tbe.counters.device_dispatches == before
    for sl, d in zip(sls, dev):
        assert d.levels == MerkleTree(sl).levels


def test_from_trees_device_flag_skips_native_gates():
    """device=True packs shapes the native SHA-NI kernel refuses (leaf
    + tag > 4096 bytes) — the device walk has no such limit."""
    leaves = [bytes(range(256)) * 20] * 4  # 5120-byte leaves
    trees = [MerkleTree(leaves)] * 2
    assert PackedProofs.from_trees(trees, 4, device=False) is None
    packed = PackedProofs.from_trees(trees, 4, device=True)
    assert packed is not None and len(packed) == 8


# ---------------------------------------------------------------------------
# Kill switch: HBBFT_TPU_NO_DEVICE_RS=1 is the host path, bit for bit
# ---------------------------------------------------------------------------


def test_kill_switch_restores_host_path(tbe, monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_NO_DEVICE_RS", "1")
    rng = random.Random(3)
    codec = RSCodec(3, 2)
    datas = [bytes(rng.randrange(256) for _ in range(20)) for _ in range(4)]
    sls = _shard_lists(rng, trees=2, n=4, leaf_len=9)
    before = tbe.counters.device_dispatches
    enc = tbe.rs_encode_batch(codec, datas)
    holes = [list(e) for e in enc]
    holes[1][0] = None
    rec = tbe.rs_reconstruct_batch(codec, holes)
    trees = tbe.merkle_build_batch(sls)
    packed = PackedProofs.from_trees(
        [MerkleTree(list(sl)) for sl in sls], 4, device=True
    )
    verdicts = tbe.merkle_verify_batch(packed, reps=2)
    assert tbe.counters.device_dispatches == before, (
        "kill switch must route every plane op to the host codec"
    )
    assert enc == [codec.encode(d) for d in datas]
    assert rec == [codec.reconstruct(list(h)) for h in holes]
    assert [t.levels for t in trees] == [MerkleTree(list(sl)).levels for sl in sls]
    assert verdicts == [True] * len(packed)


# ---------------------------------------------------------------------------
# The N=16 engine A/B: bucket fold with bit-identical Batches
# ---------------------------------------------------------------------------


class MockDeviceRsBackend(MockBackend):
    """Mock crypto + the REAL device erasure/hash plane.

    Tier-1's bucket-fold A/B vehicle: full TpuBackend epochs need the BLS
    kernel compiles, but the RS/Merkle jits are small on XLA:CPU.  Never
    sets ``pipeline_chunk``, so MockBackend._piped_submit (which assumes
    the huge-depth mock pipe) is unused — ``_pipe`` is replaced with a
    real counted DispatchPipeline so the borrowed plane methods bill
    device_seconds_* exactly as TpuBackend does.
    """

    device_rs_plane = True

    def __init__(self):
        super().__init__()
        self._pipe = DispatchPipeline(
            counters=self.counters, tracer_ref=lambda: self.tracer
        )
        self._rs_enc_bits = {}
        self._rs_dec_cache = DecodeMatrixCache()

    _host_assembly = TpuBackend._host_assembly
    _place = TpuBackend._place
    _pad_bucket = TpuBackend._pad_bucket
    _dispatch_fetch = TpuBackend._dispatch_fetch
    _dispatch_async = TpuBackend._dispatch_async
    rs_encode_batch = TpuBackend.rs_encode_batch
    rs_reconstruct_batch = TpuBackend.rs_reconstruct_batch
    merkle_build_batch = TpuBackend.merkle_build_batch
    merkle_verify_batch = TpuBackend.merkle_verify_batch


def _contribs(ids, seed, size=24):
    rng = random.Random(seed)
    return {i: bytes(rng.randrange(256) for _ in range(size)) for i in ids}


def _run_engine_arm(no_device_rs, monkeypatch, n=16):
    if no_device_rs:
        monkeypatch.setenv("HBBFT_TPU_NO_DEVICE_RS", "1")
    else:
        monkeypatch.delenv("HBBFT_TPU_NO_DEVICE_RS", raising=False)
    be = MockDeviceRsBackend()
    net = ArrayHoneyBadgerNet(range(n), backend=be, seed=3)
    # equal-size contributions per epoch keep the SHA entry-point shapes
    # identical across epochs (one tree_levels + one verify_proofs compile)
    batches = [net.run_epoch(_contribs(net.ids, seed=s)) for s in (5, 6)]
    reports = [dataclasses.asdict(r) for r in net.reports]
    for r in reports:
        # wall-clock attribution, not part of the identity contract
        r.pop("phase_seconds", None)
    return batches, reports, be.counters


def test_device_rs_engine_ab_n16(monkeypatch):
    """The acceptance invariant: with the device plane on, Batches and
    EpochReports are bit-identical to the kill-switch arm while the
    epoch's RS/Merkle work reappears attributed under device_seconds_*
    (the host-bucket fold), and the kill-switch arm dispatches nothing."""
    dev = _run_engine_arm(False, monkeypatch)
    host = _run_engine_arm(True, monkeypatch)
    assert dev[0] == host[0], "device RS plane changed Batch outputs"
    assert dev[1] == host[1], "device RS plane changed EpochReport"
    cd, ch = dev[2], host[2]
    assert ch.device_dispatches == 0
    assert ch.device_seconds_rs_enc == ch.device_seconds_merkle == 0.0
    assert cd.device_dispatches > 0
    assert cd.device_seconds_rs_enc > 0.0, "encode did not ride the plane"
    assert cd.device_seconds_merkle > 0.0, "Merkle did not ride the plane"
    # a fault-free epoch reconstructs from full shard sets — zero GF math
    # on either arm, so no decode dispatches (parity is pinned in the
    # direct fuzz above)
    assert cd.device_seconds_rs_dec == 0.0
    # the buckets-sum-to-host_seconds invariant holds with folded buckets
    for c in (cd, ch):
        from hbbft_tpu.obs import HOST_BUCKETS

        total = sum(getattr(c, f"host_bucket_{b}") for b in HOST_BUCKETS)
        assert total == pytest.approx(c.host_seconds, rel=1e-6)
