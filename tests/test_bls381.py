"""BLS12-381 golden-reference tests: curve laws, pairing bilinearity, and
the full threshold-crypto stack running over the real curve.

These anchor correctness for the JAX/TPU limb kernels (hbbft_tpu/ops/),
which are golden-tested against this module.  Marked partially slow: a
Python pairing costs ~0.4s.
"""

import random

import pytest

from hbbft_tpu.crypto import bls381 as B
from hbbft_tpu.crypto.backend import CpuBackend
from hbbft_tpu.crypto.field import Q, R
from hbbft_tpu.crypto.keys import SecretKey, SecretKeySet


def test_generators_and_orders():
    assert B.g1_on_curve(B.G1_GEN)
    assert B.g2_on_curve(B.G2_GEN)
    assert B.ec_mul(B.FQ, R, B.G1_GEN) is None
    assert B.ec_mul(B.FQ2, R, B.G2_GEN) is None


def test_ec_group_laws():
    rng = random.Random(0)
    a, b = rng.randrange(R), rng.randrange(R)
    P = B.ec_mul(B.FQ, a, B.G1_GEN)
    Qp = B.ec_mul(B.FQ, b, B.G1_GEN)
    # commutativity + distributivity of scalar mult
    assert B.ec_add(B.FQ, P, Qp) == B.ec_add(B.FQ, Qp, P)
    assert B.ec_add(B.FQ, P, Qp) == B.ec_mul(B.FQ, (a + b) % R, B.G1_GEN)
    # inverse
    assert B.ec_add(B.FQ, P, B.ec_neg(B.FQ, P)) is None
    # same over Fq2
    P2 = B.ec_mul(B.FQ2, a, B.G2_GEN)
    Q2 = B.ec_mul(B.FQ2, b, B.G2_GEN)
    assert B.ec_add(B.FQ2, P2, Q2) == B.ec_mul(B.FQ2, (a + b) % R, B.G2_GEN)


def test_fq2_fq6_fq12_field_laws():
    rng = random.Random(1)

    def r2():
        return (rng.randrange(Q), rng.randrange(Q))

    a, b, c = r2(), r2(), r2()
    assert B.fq2_mul(a, b) == B.fq2_mul(b, a)
    assert B.fq2_mul(a, B.fq2_add(b, c)) == B.fq2_add(B.fq2_mul(a, b), B.fq2_mul(a, c))
    assert B.fq2_mul(a, B.fq2_inv(a)) == B.FQ2_ONE

    a6 = (r2(), r2(), r2())
    b6 = (r2(), r2(), r2())
    assert B.fq6_mul(a6, b6) == B.fq6_mul(b6, a6)
    assert B.fq6_mul(a6, B.fq6_inv(a6)) == B.FQ6_ONE
    # v³ = ξ: multiplying by v three times == multiplying by ξ
    v3 = B.fq6_mul_by_v(B.fq6_mul_by_v(B.fq6_mul_by_v(a6)))
    xi_a = tuple(B.fq2_mul_xi(x) for x in a6)
    assert v3 == xi_a

    a12 = (a6, b6)
    assert B.fq12_mul(a12, B.fq12_inv(a12)) == B.FQ12_ONE
    # w² = v
    assert B.fq12_mul(B.FQ12_W, B.FQ12_W) == B.FQ12_W2


def test_fq2_sqrt():
    rng = random.Random(2)
    for _ in range(10):
        a = (rng.randrange(Q), rng.randrange(Q))
        sq = B.fq2_sqr(a)
        s = B.fq2_sqrt(sq)
        assert s is not None and B.fq2_sqr(s) == sq


@pytest.mark.slow
def test_pairing_bilinearity():
    e = B.pairing(B.G1_GEN, B.G2_GEN)
    assert e != B.FQ12_ONE
    # e(aP, bQ) == e(P,Q)^(ab) == e(bP, aQ)
    a, b = 5, 11
    lhs = B.pairing(B.ec_mul(B.FQ, a, B.G1_GEN), B.ec_mul(B.FQ2, b, B.G2_GEN))
    assert lhs == B.fq12_pow(e, a * b)
    rhs = B.pairing(B.ec_mul(B.FQ, b, B.G1_GEN), B.ec_mul(B.FQ2, a, B.G2_GEN))
    assert lhs == rhs
    # additivity in the first argument
    p3 = B.ec_add(B.FQ, B.G1_GEN, B.ec_mul(B.FQ, 2, B.G1_GEN))
    assert B.pairing(p3, B.G2_GEN) == B.fq12_pow(e, 3)


def test_serialization_roundtrip():
    rng = random.Random(3)
    for _ in range(3):
        k = rng.randrange(R)
        p1 = B.ec_mul(B.FQ, k, B.G1_GEN)
        p2 = B.ec_mul(B.FQ2, k, B.G2_GEN)
        assert B.g1_from_bytes(B.g1_to_bytes(p1)) == p1
        assert B.g2_from_bytes(B.g2_to_bytes(p2)) == p2
    assert B.g1_from_bytes(B.g1_to_bytes(None)) is None
    assert B.g2_from_bytes(B.g2_to_bytes(None)) is None
    with pytest.raises(ValueError):
        B.g1_from_bytes(b"\x00" * 48)


def test_hash_to_curve_subgroup_and_determinism():
    h1 = B.hash_to_g1(b"doc")
    h2 = B.hash_to_g2(b"doc")
    assert B.hash_to_g1(b"doc") == h1  # deterministic
    assert B.hash_to_g2(b"doc") == h2
    assert B.hash_to_g1(b"other") != h1
    assert B.ec_mul(B.FQ, R, h1) is None  # in the r-subgroup
    assert B.ec_mul(B.FQ2, R, h2) is None


@pytest.mark.slow
def test_threshold_stack_on_real_curve():
    """The full generic threshold layer over real BLS12-381: sign share,
    verify share (pairing), combine, verify combined; encrypt, decrypt
    share, verify share, combine."""
    backend = CpuBackend()
    g = backend.group
    rng = random.Random(4)
    sk_set = SecretKeySet.random(g, threshold=1, rng=rng)
    pk_set = sk_set.public_keys()
    doc = b"the doc"
    shares = {i: sk_set.secret_key_share(i).sign_share(doc) for i in range(3)}
    assert pk_set.public_key_share(0).verify_sig_share(shares[0], doc)
    bad = sk_set.secret_key_share(0).sign_share(b"bad")
    assert not pk_set.public_key_share(0).verify_sig_share(bad, doc)
    sig_a = pk_set.combine_signatures({i: shares[i] for i in (0, 1)})
    sig_b = pk_set.combine_signatures({i: shares[i] for i in (1, 2)})
    assert sig_a == sig_b
    assert pk_set.public_key().verify(sig_a, doc)

    msg = b"sixteen byte msg"
    ct = pk_set.encrypt(msg, rng)
    assert ct.verify()
    dshares = {}
    for i in (0, 2):
        d = sk_set.secret_key_share(i).decrypt_share(ct)
        assert pk_set.public_key_share(i).verify_decryption_share(d, ct)
        dshares[i] = d
    assert pk_set.combine_decryption_shares(dshares, ct) == msg


@pytest.mark.slow
def test_plain_bls_signature_on_real_curve():
    g = CpuBackend().group
    rng = random.Random(5)
    sk = SecretKey.random(g, rng)
    sig = sk.sign(b"m")
    assert sk.public_key().verify(sig, b"m")
    assert not sk.public_key().verify(sig, b"n")


def test_subgroup_membership_checks():
    """The fast endomorphism checks (φ eigenvalue on G1, ψ eigenvalue on
    G2) must accept order-r points and reject on-curve cofactor-torsion
    points — the device-ladder precondition enforced at deserialization
    (the reference's pairing crate makes the same guarantee in its
    checked decode; SURVEY.md §2.2 threshold_crypto row)."""
    rng = random.Random(11)
    for _ in range(3):
        k = rng.randrange(1, B.R)
        assert B.g1_in_subgroup(B.ec_mul(B.FQ, k, B.G1_GEN))
        assert B.g2_in_subgroup(B.ec_mul(B.FQ2, k, B.G2_GEN))
    assert B.g1_in_subgroup(None) and B.g2_in_subgroup(None)

    # on-curve G1 point with a cofactor component: x-search, no clearing
    x = 1
    while True:
        y = B._fq_sqrt((x * x * x + B.G1_B) % B.Q)
        if y is not None and B.ec_mul(B.FQ, B.R, (x, y)) is not None:
            torsion1 = (x, y)
            break
        x += 1
    assert B.g1_on_curve(torsion1)
    assert not B.g1_in_subgroup(torsion1)
    with pytest.raises(ValueError, match="subgroup"):
        B.g1_from_bytes(B.g1_to_bytes(torsion1))

    # same for G2 on the twist
    b2 = B.fq2_scalar(B.fq2_mul_xi(B.FQ2_ONE), 4)
    x0 = 1
    while True:
        xx = (x0, 0)
        yy = B.fq2_sqrt(B.fq2_add(B.fq2_mul(B.fq2_sqr(xx), xx), b2))
        if yy is not None and B.ec_mul(B.FQ2, B.R, (xx, yy)) is not None:
            torsion2 = (xx, yy)
            break
        x0 += 1
    assert B.g2_on_curve(torsion2)
    assert not B.g2_in_subgroup(torsion2)
    with pytest.raises(ValueError, match="subgroup"):
        B.g2_from_bytes(B.g2_to_bytes(torsion2))

    # round-trip of legitimate points still works through the check
    p = B.ec_mul(B.FQ, 12345, B.G1_GEN)
    assert B.g1_from_bytes(B.g1_to_bytes(p)) == p
    q = B.ec_mul(B.FQ2, 54321, B.G2_GEN)
    assert B.g2_from_bytes(B.g2_to_bytes(q)) == q


def test_non_canonical_infinity_rejected():
    """Infinity must have exactly one byte-level encoding (the reference's
    checked decode rejects malleable encodings the same way)."""
    assert B.g1_from_bytes(B.g1_to_bytes(None)) is None
    assert B.g2_from_bytes(B.g2_to_bytes(None)) is None
    bad1 = bytearray(B.g1_to_bytes(None)); bad1[-1] = 1
    with pytest.raises(ValueError, match="canonical"):
        B.g1_from_bytes(bytes(bad1))
    bad2 = bytearray(B.g2_to_bytes(None)); bad2[0] |= 0b0010_0000  # sign bit
    with pytest.raises(ValueError, match="canonical"):
        B.g2_from_bytes(bytes(bad2))


def test_fast_cofactor_clearing():
    """Budroni–Pintore G2 clearing and the [1−u] G1 clearing must land
    every on-curve point in the r-order subgroup (they define the
    hash-to-curve outputs), and hashing must stay deterministic."""
    rng = random.Random(17)
    for _ in range(3):
        h1 = B.hash_to_g1(bytes([rng.randrange(256), rng.randrange(256)]))
        h2 = B.hash_to_g2(bytes([rng.randrange(256), rng.randrange(256)]))
        assert B.g1_on_curve(h1) and B.g1_in_subgroup(h1)
        assert B.g2_on_curve(h2) and B.g2_in_subgroup(h2)
        assert B.ec_mul(B.FQ, B.R, h1) is None
        assert B.ec_mul(B.FQ2, B.R, h2) is None
    assert B.hash_to_g2(b"det") == B.hash_to_g2(b"det")
    # the fast path must agree with the slow full-cofactor clearing up to
    # subgroup membership on a raw (pre-clear) twist point
    x0 = 1
    bb = B.fq2_scalar(B.fq2_mul_xi(B.FQ2_ONE), 4)
    while True:
        xx = (x0, 0)
        yy = B.fq2_sqrt(B.fq2_add(B.fq2_mul(B.fq2_sqr(xx), xx), bb))
        if yy is not None:
            raw = (xx, yy)
            break
        x0 += 1
    assert not B.g2_in_subgroup(raw)  # clearing actually does something
    fast = B.clear_cofactor_g2(raw)
    assert B.g2_in_subgroup(fast)
    # both clearings land in the subgroup (the BP output is a fixed
    # nonzero scalar multiple of the naive one, so membership is the
    # shared invariant being checked here)
    slow = B.ec_mul(B.FQ2, B.G2_COFACTOR, raw)
    assert B.g2_in_subgroup(slow)
    assert fast is not None and slow is not None
    # identity handling matches the G1 helper
    assert B.clear_cofactor_g2(None) is None
    assert B.clear_cofactor_g1(None) is None
