"""Crash/restart fault injection (net/crash.py) + the composed gauntlet
(net/scenarios.py Cell runner): a node dies mid-epoch, restores from its
last utils/snapshot checkpoint, replays its WAL bit-identically (peers
never see a restart as equivocation), catches up through the sender-queue
window, and commits the same Batches — composed with adversaries, network
schedules, era-change churn, and client traffic, all seeded-replayable.

The N=16 x 200-epoch acceptance cell runs slow-marked; tier-1 covers the
same composition at small N (~0.5 s per cell)."""

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.net.scenarios import (
    CHURNS,
    CRASHES,
    TRAFFICS,
    Cell,
    run_cell,
)
from hbbft_tpu.net.virtual_net import (
    CrankError,
    CrashEvent,
    CrashSchedule,
    NetBuilder,
)
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadgerBuilder
from hbbft_tpu.protocols.sender_queue import SenderQueue


def _qhb_net(n=4, f=0, crash=None, seed=5, batch_size=3):
    def make(ni, be, rng):
        return SenderQueue(
            QueueingHoneyBadgerBuilder(ni, be, rng)
            .batch_size(batch_size)
            .build()
        )

    return (
        NetBuilder(range(n))
        .num_faulty(f)
        .backend(MockBackend())
        .crashes(crash)
        .crank_limit(2_000_000)
        .using(make)
        .build(seed=seed)
    )


def _boot(net):
    for i in sorted(net.nodes):
        net.send_input(i, ("user", ("boot", i)))


def _run_epochs(net, epochs, max_cranks=400_000):
    def live_done(nt, k):
        down = nt.down_node_ids()
        return all(
            len(nd.outputs) >= k + 1
            for nd in nt.correct_nodes()
            if nd.id not in down
        )

    for k in range(epochs):
        net.crank_until(lambda nt, k=k: live_done(nt, k), max_cranks=max_cranks)


def _faults(net):
    return [
        (n.id, f.kind) for n in net.nodes.values() for f in n.faults_observed
    ]


# ---------------------------------------------------------------------------
# The crash/restart axis itself
# ---------------------------------------------------------------------------


def test_crash_parks_traffic_and_restart_catches_up():
    """A node dies at epoch 3, its traffic parks instead of delivering,
    and after restart it recommits the same Batches as everyone else."""
    cs = CrashSchedule(
        (CrashEvent(node_id=3, at_epoch=3, down_epochs=4),),
        checkpoint_every=2,
    )
    net = _qhb_net(crash=cs)
    _boot(net)
    _run_epochs(net, 14)
    st = net.crash.stats()
    assert st["crashes"] == 1 and st["restarts"] == 1
    assert net.counters.crash_parked_messages > 0
    assert net.counters.crash_checkpoints >= 1
    # replay was bit-identical: every re-emitted message matched the
    # sent log, so nothing was double-delivered and no fault recorded
    assert net.counters.crash_suppressed_sends > 0
    assert _faults(net) == []
    common = min(len(n.outputs) for n in net.nodes.values())
    assert common >= 14
    ref = net.nodes[0].outputs[:common]
    for i in net.nodes:
        assert net.nodes[i].outputs[:common] == ref, f"node {i} diverged"
    rec = st["recoveries"][0]
    assert rec["replayed_events"] > 0
    assert rec["behind_after_replay"] >= 0


def test_restart_restores_from_mid_epoch_checkpoint():
    """checkpoint_every=1 forces the recovery point between epochs; the
    WAL replay then crosses epoch state mid-flight.  The restored node
    must still match the network bit for bit."""
    cs = CrashSchedule(
        (CrashEvent(node_id=2, at_epoch=2, down_epochs=3),),
        checkpoint_every=1,
    )
    net = _qhb_net(crash=cs, seed=9)
    _boot(net)
    _run_epochs(net, 10)
    assert net.crash.stats()["restarts"] == 1
    assert _faults(net) == []
    common = min(len(n.outputs) for n in net.nodes.values())
    ref = net.nodes[0].outputs[:common]
    for i in net.nodes:
        assert net.nodes[i].outputs[:common] == ref


def test_down_node_inputs_park_and_apply_at_restart():
    """send_input to a dead node returns an empty Step; the parked input
    lands after restart (the client-retry model) and commits."""
    cs = CrashSchedule(
        (CrashEvent(node_id=3, at_epoch=2, down_epochs=3),), checkpoint_every=2
    )
    net = _qhb_net(crash=cs, seed=7)
    _boot(net)
    _run_epochs(net, 3)
    assert net.crash.is_down(3), "node 3 should be down by epoch 3"
    step = net.send_input(3, ("user", ("late", "tx")))
    assert not step.output and not step.messages
    assert net.crash.tracks[3].parked_inputs
    _run_epochs(net, 12)
    assert not net.crash.is_down(3)
    committed = {
        tx
        for b in net.nodes[0].outputs
        for txs in b.contributions.values()
        if isinstance(txs, list)
        for tx in txs
    }
    assert ("late", "tx") in committed


def test_corrupted_checkpoint_is_attributed_not_raised():
    """An unreadable checkpoint must surface as crash:recovery_failed
    against the crashed node — the run continues, the harness never
    raises, and the node stays down."""
    cs = CrashSchedule(
        (CrashEvent(node_id=3, at_epoch=2, down_epochs=2),), checkpoint_every=2
    )
    net = _qhb_net(crash=cs, seed=3)
    _boot(net)
    _run_epochs(net, 2)
    # arm() took the baseline checkpoint; corrupt whatever is current
    net.crash.tracks[3].ckpt_blob = b"HBTPUSNAP1corrupt"
    _run_epochs(net, 8)
    kinds = [k for _, k in _faults(net)]
    assert "crash:recovery_failed" in kinds
    assert net.crash.tracks[3].state == "failed"
    # the other three nodes carried the run (f-budget covers the loss)
    live = [n for n in net.correct_nodes() if n.id != 3]
    assert all(len(n.outputs) >= 8 for n in live)


def test_why_stalled_names_down_node():
    """A cell starved by a dead node names it: 'node X down since crank
    N / restoring from checkpoint at epoch e'."""
    from hbbft_tpu.net.adversary import SilentAdversary

    cs = CrashSchedule(
        (CrashEvent(at_epoch=1, down_epochs=None, down_ticks=None,
                    restart=False),),
        checkpoint_every=2,
    )

    # one truly silent faulty node + one dead honest node leaves 2 live
    # participants — below every N-f=3 quorum, so the net starves and
    # the diagnosis must name the outage
    def make(ni, be, rng):
        return SenderQueue(
            QueueingHoneyBadgerBuilder(ni, be, rng).batch_size(3).build()
        )

    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .adversary(SilentAdversary())
        .backend(MockBackend())
        .crashes(cs)
        .crank_limit(2_000_000)
        .using(make)
        .build(seed=2)
    )
    _boot(net)
    with pytest.raises(CrankError) as ei:
        _run_epochs(net, 30)
    report = ei.value.report
    assert report is not None and "crash" in report
    text = str(ei.value)
    assert "down since crank" in text
    assert "restoring from checkpoint at epoch" in text


def test_epoch_gated_restart_released_on_starvation():
    """An epoch-gated restart whose epoch mark can never advance (the
    net starves without the dead node) fires at quiescence instead of
    deadlocking — the LaggardAdversary starvation-release convention."""
    from hbbft_tpu.net.adversary import SilentAdversary

    cs = CrashSchedule(
        (CrashEvent(at_epoch=1, down_epochs=50),), checkpoint_every=2
    )

    def make(ni, be, rng):
        return SenderQueue(
            QueueingHoneyBadgerBuilder(ni, be, rng).batch_size(3).build()
        )

    # silent faulty + dead honest = 2 live < every quorum of 3: epochs
    # freeze, so the down_epochs=50 mark would never be reached
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .adversary(SilentAdversary())
        .backend(MockBackend())
        .crashes(cs)
        .crank_limit(2_000_000)
        .using(make)
        .build(seed=6)
    )
    _boot(net)
    _run_epochs(net, 6)
    st = net.crash.stats()
    assert st["crashes"] == 1 and st["restarts"] == 1
    assert _faults(net) == []
    live = [n for n in net.correct_nodes()]
    assert all(len(n.outputs) >= 6 for n in live)


def test_tick_gated_restart_keeps_its_outage_at_idle():
    """A tick-gated restart is NOT starvation-released: when the net
    drains, the clock fast-forwards to each configured restart time in
    order instead of restarting everything at once."""
    cs = CrashSchedule(
        (
            CrashEvent(node_id=2, at=5, at_epoch=None, down_epochs=None,
                       down_ticks=100),
            CrashEvent(node_id=3, at=5, at_epoch=None, down_epochs=None,
                       down_ticks=5000),
        ),
        checkpoint_every=2,
    )
    net = _qhb_net(crash=cs, seed=8)
    _boot(net)
    _run_epochs(net, 8, max_cranks=800_000)
    # node 2's short outage is over; node 3's 5000-tick outage HOLDS —
    # before the fix, any momentary queue drain force-restarted it
    assert net.crash.stats()["restarts"] == 1
    assert net.crash.is_down(3)
    assert net.now < 5005
    _run_epochs(net, 35, max_cranks=2_000_000)
    rec3 = [r for r in net.crash.stats()["recoveries"] if r["node"] == "3"]
    assert rec3, "node 3 never restarted"
    assert net.now >= 5005, f"node 3 restarted early at now={net.now}"
    assert _faults(net) == []


def test_crash_schedule_rejects_round_defer_mode():
    """The WAL replay model needs eager crypto resolution; composing a
    crash schedule with the round barrier is a configuration error, not
    a latent replay-divergence fault."""
    cs = CrashSchedule((CrashEvent(at_epoch=1, down_epochs=2),))

    def make(ni, be, rng):
        return SenderQueue(
            QueueingHoneyBadgerBuilder(ni, be, rng).batch_size(3).build()
        )

    with pytest.raises(ValueError, match="eager"):
        (
            NetBuilder(range(4))
            .backend(MockBackend())
            .defer_mode("round")
            .crashes(cs)
            .using(make)
            .build(seed=1)
        )


def test_restored_manager_accepts_restart_listeners():
    """After a whole-net restore the env-attr fallback for
    restart_listeners is the class-level (); add_restart_listener (the
    driver's path) must still work."""
    from hbbft_tpu.utils.snapshot import load_node, save_node

    cs = CrashSchedule(
        (CrashEvent(node_id=3, at_epoch=2, down_epochs=3),), checkpoint_every=2
    )
    net = _qhb_net(crash=cs, seed=5)
    _boot(net)
    _run_epochs(net, 3)
    net2 = load_node(save_node(net), MockBackend())
    calls = []
    net2.crash.add_restart_listener(lambda nt, nid, algo: calls.append(nid))
    _run_epochs(net2, 10)
    assert net2.crash.stats()["restarts"] == 1
    assert calls == [3]


def test_second_crash_replays_through_first_recovery():
    """Two crashes of the same node: the second WAL replay crosses state
    written after the first restart (the rebind-to-shared-rng path)."""
    cell = Cell(
        attack="passive", schedule="uniform", churn="none",
        crash="two_restarts", traffic="none", n=4, epochs=14, seed=4,
    )
    r = run_cell(cell)
    assert r.ok, (r.error, r.misattributed, r.missing_expected)
    assert r.crashes == 2 and r.restarts == 2
    assert r.recovered_in_time


def test_whole_net_snapshot_mid_outage_resumes_identically():
    """A whole-net checkpoint taken WHILE a node is down carries the
    outage (parked traffic, WAL, pending restart): the restored net
    restarts the node at the same point and commits identical Batches."""
    from hbbft_tpu.utils.snapshot import load_node, save_node

    cs = CrashSchedule(
        (CrashEvent(node_id=3, at_epoch=2, down_epochs=3),), checkpoint_every=2
    )
    net = _qhb_net(crash=cs, seed=5)
    _boot(net)
    _run_epochs(net, 3)
    assert net.crash.is_down(3)
    net2 = load_node(save_node(net), MockBackend())
    assert net2.crash is not None and net2.crash.is_down(3)
    for k in range(3, 10):
        net.crank_until(
            lambda nt, k=k: all(
                len(nd.outputs) >= k + 1
                for nd in nt.correct_nodes()
                if nd.id not in nt.down_node_ids()
            ),
            max_cranks=400_000,
        )
        net2.crank_until(
            lambda nt, k=k: all(
                len(nd.outputs) >= k + 1
                for nd in nt.correct_nodes()
                if nd.id not in nt.down_node_ids()
            ),
            max_cranks=400_000,
        )
    assert net.crash.stats()["restarts"] == 1
    assert net2.crash.stats()["restarts"] == 1
    for i in net.nodes:
        assert net.nodes[i].outputs == net2.nodes[i].outputs


# ---------------------------------------------------------------------------
# The composed gauntlet
# ---------------------------------------------------------------------------


def test_registries_cover_all_axes():
    assert {"none", "era_flip"} <= set(CHURNS)
    assert {"none", "one_restart", "two_restarts"} <= set(CRASHES)
    assert {"none", "half_x", "one_x", "two_x"} <= set(TRAFFICS)


def test_composed_cell_all_axes_on():
    """attack x schedule x churn x crash x traffic in ONE cell: the
    tier-1 miniature of the acceptance soak."""
    cell = Cell(
        attack="equivocate", schedule="partition_heal", churn="era_flip",
        crash="one_restart", traffic="one_x", n=5, epochs=12, seed=3,
    )
    r = run_cell(cell)
    assert r.ok, (r.error, r.misattributed[:3], r.missing_expected)
    assert r.epochs_committed >= 12
    assert r.eras == [0, 1, 2], "era_flip churn should turn the era twice"
    assert r.crashes == 1 and r.restarts == 1 and r.recovered_in_time
    assert r.fault_kinds.get("broadcast:conflicting_values", 0) > 0
    assert not r.misattributed
    assert r.tx_committed > 0 and r.commit_p99 > 0


@pytest.mark.parametrize("seed", [3, 11])
def test_composed_cell_fingerprint_is_stable(seed):
    """Seeded replay: the same cell reproduces its fingerprint (batch
    sha256 + fault log + tracker fingerprint + crash trace) bit for bit,
    and a different seed genuinely perturbs the run."""
    cell = Cell(
        attack="crafted_shares", schedule="wan", churn="era_flip",
        crash="one_restart", traffic="one_x", n=5, epochs=10, seed=seed,
    )
    a, b = run_cell(cell), run_cell(cell)
    assert a.fingerprint() == b.fingerprint()
    assert a.ok
    other = run_cell(
        Cell(**{**cell.to_dict(), "seed": seed + 100})
    )
    assert other.fingerprint() != a.fingerprint()


def test_lossy_composed_cell_gated_bounded():
    """The lossy schedule rides the verdict matrix now: a stall under
    model-violating loss passes iff the committed prefix is identical,
    nothing was misattributed, the recovery gate held, and the stall
    names its cause."""
    cell = Cell(
        attack="withhold_echo", schedule="lossy", crash="one_restart",
        n=5, epochs=8, seed=2,
    )
    r = run_cell(cell, crank_limit=400_000)
    assert r.ok and r.bounded
    assert r.stall_named or r.epochs_committed >= 8


def test_soak_replay_record_roundtrip(tmp_path):
    """tools/soak.py reproduces a cell from its record (cell + seed +
    fingerprint) alone, and flags a fingerprint mismatch."""
    import json
    import sys

    sys.path.insert(0, "tools")
    import soak

    cell = Cell(
        attack="replay_flood", schedule="lan", crash="one_restart",
        traffic="half_x", n=4, epochs=8, seed=6,
    )
    r = run_cell(cell)
    rec = tmp_path / "cell.json"
    rec.write_text(
        json.dumps(
            {"version": 1, "cell": cell.to_dict(), "fingerprint": r.fingerprint()}
        )
    )
    assert soak.replay_record(str(rec), 5_000_000) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(
        json.dumps({"version": 1, "cell": cell.to_dict(), "fingerprint": "0" * 64})
    )
    assert soak.replay_record(str(bad), 5_000_000) == 2


# ---------------------------------------------------------------------------
# Slow arms: the acceptance-criteria soak
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2])
def test_flagship_soak_n16_200_epochs(seed):
    """ISSUE 11 acceptance: equivocator x partition_heal x churn x one
    crash+restart x 1x traffic at N=16, 200 epochs — honest Batches
    bit-identical, every fault attributed, the restarted node recommits
    within the gate, and the seeded-replay fingerprint is stable."""
    cell = Cell(
        attack="equivocate", schedule="partition_heal", churn="era_flip",
        crash="one_restart", traffic="one_x", n=16, epochs=200, seed=seed,
    )
    r = run_cell(cell, crank_limit=50_000_000)
    assert r.ok, (r.error, r.misattributed[:3], r.missing_expected)
    assert r.epochs_committed >= 200
    assert r.crashes == 1 and r.restarts == 1 and r.recovered_in_time
    assert not r.misattributed
    assert r.tx_committed > 1000
    r2 = run_cell(cell, crank_limit=50_000_000)
    assert r2.fingerprint() == r.fingerprint(), "seeded replay diverged"
