"""Property-based tests — the reference's proptest strategy, in hypothesis.

Mirrors `tests/net/proptest.rs` § (SURVEY.md §4): a `NetworkDimension`-style
strategy samples valid (N, f) pairs with f < N/3, runs protocol nets under
randomly drawn adversaries and seeds, and asserts the consensus invariants.
Hypothesis shrinks failures to minimal dimensions, like proptest.
"""

import pytest

pytest.importorskip("hypothesis", reason="hypothesis not installed in this image")

from hypothesis import HealthCheck, given, settings, strategies as st

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.net.adversary import (
    NodeOrderAdversary,
    NullAdversary,
    ReorderingAdversary,
    SilentAdversary,
)
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.threshold_sign import ThresholdSign


@st.composite
def network_dimension(draw, max_nodes=10):
    """Valid (n, f): 1 ≤ n ≤ max_nodes, f < n/3 (NetworkDimension §)."""
    n = draw(st.integers(min_value=1, max_value=max_nodes))
    max_f = max(0, (n - 1) // 3)
    f = draw(st.integers(min_value=0, max_value=max_f))
    return (n, f)


adversaries = st.sampled_from(
    [NullAdversary, ReorderingAdversary, NodeOrderAdversary]
)

_settings = settings(
    max_examples=20,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)


@given(dim=network_dimension(), adv=adversaries, seed=st.integers(0, 2**16))
@_settings
def test_threshold_sign_agreement(dim, adv, seed):
    n, f = dim
    net = (
        NetBuilder(range(n))
        .num_faulty(f)
        .adversary(adv())
        .defer_mode("round")
        .using(lambda ni, be: ThresholdSign(ni, be, doc=b"prop"))
        .build(seed=seed)
    )
    net.broadcast_input(None)
    net.crank_to_quiescence()
    outs = [node.outputs for node in net.correct_nodes()]
    assert all(len(o) == 1 for o in outs)
    assert all(o == outs[0] for o in outs)


@given(
    dim=network_dimension(max_nodes=8),
    adv=adversaries,
    seed=st.integers(0, 2**16),
    value=st.binary(min_size=1, max_size=64),
)
@_settings
def test_broadcast_agreement(dim, adv, seed, value):
    n, f = dim
    net = (
        NetBuilder(range(n))
        .num_faulty(f)
        .adversary(adv())
        .using(lambda ni, be: Broadcast(ni, proposer_id=0))
        .build(seed=seed)
    )
    # Only deliver the proposal if the proposer is correct; a faulty
    # proposer may equivocate, in which case all-or-nothing must hold.
    if not net.nodes[0].faulty:
        net.send_input(0, value)
        net.crank_to_quiescence()
        outs = [node.outputs for node in net.correct_nodes()]
        assert all(o == [value] for o in outs)
    else:
        net.crank_to_quiescence()


@given(
    dim=network_dimension(max_nodes=7),
    seed=st.integers(0, 2**16),
    proposals=st.lists(st.booleans(), min_size=7, max_size=7),
)
@_settings
def test_binary_agreement_decides_same(dim, seed, proposals):
    n, f = dim
    net = (
        NetBuilder(range(n))
        .num_faulty(f)
        .defer_mode("round")
        .using(lambda ni, be: BinaryAgreement(ni, be, session_id=b"prop-ba"))
        .build(seed=seed)
    )
    for i in range(n):
        net.send_input(i, proposals[i % len(proposals)])
    net.crank_to_quiescence()
    outs = [node.outputs for node in net.correct_nodes()]
    assert all(len(o) == 1 for o in outs)
    decided = {o[0] for o in outs}
    assert len(decided) == 1
    # Validity: the decision must be someone's proposal.
    assert decided.pop() in set(proposals[:n])


@given(seed=st.integers(0, 2**16), data=st.binary(min_size=0, max_size=512))
@settings(max_examples=60, deadline=None)
def test_wire_decode_never_executes_or_crashes(seed, data):
    """Arbitrary bytes into the wire decoder: either a message object or
    WireError — no other exception type, no code execution."""
    from hbbft_tpu.crypto.backend import MockBackend
    from hbbft_tpu.utils.wire import WireError, decode_message, encode_message

    group = MockBackend().group
    try:
        msg = decode_message(data, group)
    except WireError:
        return
    # decodable garbage must re-encode deterministically
    assert isinstance(encode_message(msg), bytes)


@given(
    n=st.integers(4, 9),
    seed=st.integers(0, 2**12),
    payload=st.integers(1, 64),
)
@settings(max_examples=15, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
def test_array_engine_agreement_property(n, seed, payload):
    """Any network size / seed / payload size: all nodes output the same
    batch containing every proposer's contribution."""
    import random as _random

    from hbbft_tpu.crypto.backend import MockBackend
    from hbbft_tpu.engine import ArrayHoneyBadgerNet

    rng = _random.Random(seed)
    net = ArrayHoneyBadgerNet(range(n), backend=MockBackend(), seed=seed)
    contribs = {
        i: bytes(rng.randrange(256) for _ in range(payload)) for i in range(n)
    }
    batches = net.run_epoch(contribs)
    first = batches[0]
    assert all(batches[i] == first for i in range(n))
    assert first.contributions == contribs
