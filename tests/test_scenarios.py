"""Adversary × network-schedule scenario matrix (ROADMAP item 4).

Tier-1 runs the fast subset — N∈{4,7}, every attack × two schedules,
plus N=4 across every eventual-delivery schedule — asserting the three
matrix invariants per cell: all honest nodes commit identical Batches,
every injected misbehaviour lands in the fault log with the expected
kind against a faulty node, and no fault is ever attributed to an honest
node.  The full N=16 matrix and the N=100/f=33 arm are slow-marked.

Also covered here: seeded replay determinism (same seed ⇒ identical
fault log + batch digest), the schedule layer's delivery semantics, and
the CrankError why-stalled diagnosis naming the attack and partition.
"""

import pytest

from hbbft_tpu.core.fault_log import all_fault_kinds
from hbbft_tpu.net.scenarios import (
    ATTACKS,
    MATRIX_ATTACKS,
    MATRIX_SCHEDULES,
    SCHEDULES,
    build_scenario_net,
    run_matrix,
    run_scenario,
)
from hbbft_tpu.net.virtual_net import (
    CrankError,
    NetBuilder,
    NetSchedule,
    Partition,
)


def _cell_ok(r):
    assert r.ok, (
        f"{r.attack}x{r.schedule}@n{r.n}: error={r.error} "
        f"missing={r.missing_expected} misattributed={r.misattributed[:3]} "
        f"identical={r.batches_identical} epochs={r.epochs_committed}"
    )


# ---------------------------------------------------------------------------
# The fast matrix subset (tier-1)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("attack", MATRIX_ATTACKS)
def test_fast_matrix_cell(attack):
    """Every attack × {uniform, partition_heal} at N∈{4,7}."""
    for n in (4, 7):
        for schedule in ("uniform", "partition_heal"):
            _cell_ok(run_scenario(attack, schedule, n, seed=1))


@pytest.mark.parametrize("schedule", MATRIX_SCHEDULES)
def test_fast_matrix_schedules(schedule):
    """Every eventual-delivery schedule × every attack at N=4."""
    for attack in MATRIX_ATTACKS:
        _cell_ok(run_scenario(attack, schedule, 4, seed=2))


def test_matrix_covers_acceptance_shape():
    """The registries satisfy the acceptance floor: ≥6 attacks × ≥4
    eventual-delivery schedules, expectations all registered kinds."""
    assert len(MATRIX_ATTACKS) >= 6
    assert len(MATRIX_SCHEDULES) >= 4
    known = all_fault_kinds()
    for name in MATRIX_ATTACKS:
        for kind in ATTACKS[name].expected_faults:
            assert kind in known, f"{name} expects unregistered {kind}"
    # at least one attack plants each family of provable evidence
    planted = {k for a in ATTACKS.values() for k in a.expected_faults}
    assert "broadcast:conflicting_values" in planted
    assert "threshold_decrypt:invalid_share" in planted
    assert "broadcast:multiple_echos" in planted


@pytest.mark.parametrize("attack", MATRIX_ATTACKS)
def test_lossy_cells_gated_bounded(attack):
    """The lossy schedule is back in the verdict matrix (it was flagged
    out of the LIVENESS matrix in PR 7): under the bounded-degradation
    contract a lossy cell passes iff the common committed prefix is
    identical, no fault was misattributed, and a stall names its cause —
    liveness and expected-fault evidence are waived (a dropped message
    may starve a quorum or swallow the attack's proof)."""
    from hbbft_tpu.net.scenarios import MATRIX_SCHEDULES_ALL

    assert "lossy" in MATRIX_SCHEDULES_ALL
    for seed in (1, 5):
        r = run_scenario(attack, "lossy", 4, seed=seed, crank_limit=200_000)
        assert r.ok, (
            f"{attack}xlossy seed={seed}: error={r.error} "
            f"misattr={r.misattributed[:3]} prefix={r.prefix_identical}"
        )
        if r.error is not None:
            assert r.bounded  # degraded pass, visibly flagged
            assert (r.why or {}).get("summary"), "stall must name a cause"


def test_first_scheduler_mode():
    """The matrix invariants hold under the deterministic 'first'
    scheduler too (the schedule layer composes with either)."""
    for attack in ("equivocate", "crafted_shares"):
        _cell_ok(
            run_scenario(attack, "lan", 4, seed=3, scheduler="first")
        )


# ---------------------------------------------------------------------------
# Seeded replay determinism
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("attack,schedule", [
    ("crafted_shares", "wan"),
    ("equivocate", "partition_heal"),
    ("replay_flood", "lan"),
])
def test_seeded_replay_is_bit_identical(attack, schedule):
    """Same seed ⇒ identical fault log and batch digests: every attack
    and the schedule layer draw entropy only from net.rng."""
    a = run_scenario(attack, schedule, 4, seed=11)
    b = run_scenario(attack, schedule, 4, seed=11)
    assert a.fault_log == b.fault_log
    assert a.batch_digest == b.batch_digest
    assert a.cranks == b.cranks
    assert a.schedule_delayed == b.schedule_delayed
    assert a.schedule_dropped == b.schedule_dropped
    # and a different seed genuinely perturbs delivery
    c = run_scenario(attack, schedule, 4, seed=12)
    assert c.ok and (c.cranks != a.cranks or c.fault_log != a.fault_log)


# ---------------------------------------------------------------------------
# Schedule-layer semantics
# ---------------------------------------------------------------------------


def _build_hb(n, schedule, seed=0, crank_limit=500_000):
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    return (
        NetBuilder(range(n))
        .num_faulty(1)
        .schedule(schedule)
        .crank_limit(crank_limit)
        .using(lambda ni, be: HoneyBadger(ni, be, session_id=b"sched"))
        .build(seed=seed)
    )


def test_latency_delays_but_delivers():
    net = _build_hb(4, NetSchedule(name="t", latency=3, jitter=2))
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    net.crank_until(
        lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
    )
    assert net.counters.schedule_delayed > 0
    assert net.counters.schedule_dropped == 0


def test_drop_schedule_counts_drops():
    net = _build_hb(7, NetSchedule(name="t", drop=0.2), seed=5)
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    try:
        net.crank_to_quiescence()
    except CrankError:
        pass  # a lossy run may legitimately starve
    assert net.counters.schedule_dropped > 0


def test_partition_holds_cross_traffic_until_heal():
    """During [start, end) no cross-partition message is delivered; the
    virtual clock fast-forwards to the heal instead of starving."""
    sched = NetSchedule(
        name="t",
        partitions=(Partition(0, 10_000, (frozenset({0, 1}),)),),
    )
    net = _build_hb(4, sched)
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    part = sched.partitions[0]
    held_seen = 0
    # while the virtual clock is inside the partition window, no
    # deliverable message crosses the boundary — cross traffic parks on
    # the future heap, dated to the heal
    for _ in range(200):
        if net.now >= part.end:
            break
        for m in net.queue:
            assert not part.crosses(m.sender, m.to), (m.sender, m.to)
        for not_before, _seq, m in net._future:
            if part.crosses(m.sender, m.to):
                assert not_before >= part.end, (m.sender, m.to, not_before)
                held_seen += 1
        if net.crank() is None:
            break
    assert held_seen > 0, "no cross-partition traffic was ever held"
    # and the run still completes after the heal (clock fast-forwards)
    net.crank_until(
        lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
    )


def test_partition_drop_mode_severs_links():
    sched = NetSchedule(
        name="t",
        partitions=(Partition(0, 10**9, (frozenset({2, 3}),)),),
        partition_mode="drop",
    )
    net = _build_hb(4, sched)
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    with pytest.raises(CrankError):
        net.crank_until(
            lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
        )
    assert net.counters.schedule_dropped > 0


# ---------------------------------------------------------------------------
# CrankError diagnosis (satellite: no more bare limit trips)
# ---------------------------------------------------------------------------


def test_crank_error_names_attack_and_partition():
    """A starved cell's CrankError carries the why-stalled report naming
    the scenario, the adversary, and the partition isolating nodes."""
    from hbbft_tpu.net.scenarios import ScheduleSpec

    spec = ScheduleSpec(
        "split_forever",
        lambda n: NetSchedule(
            name="split_forever",
            partitions=(Partition(0, 10**9, (frozenset({2, 3}),)),),
            partition_mode="drop",
        ),
    )
    net = build_scenario_net(
        ATTACKS["crafted_shares"], spec, 4, seed=1, crank_limit=100_000
    )
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    with pytest.raises(CrankError) as ei:
        net.crank_until(
            lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
        )
    err = ei.value
    assert err.report is not None
    ctx = err.report["scenario"]
    assert ctx["adversary"]["name"] == "CraftedShareAdversary"
    assert "crafted_shares" in ctx["scenario"]
    assert ctx["schedule"]["partition"]["isolates"] == [[2, 3]]
    text = str(err)
    assert "partition isolates {2, 3}" in text
    assert "CraftedShareAdversary" in text
    # and the starved instances are still named underneath the context
    assert err.report["nodes"], "starved protocol instances missing"


def test_crank_limit_trip_carries_report():
    net = _build_hb(4, None, crank_limit=10)
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    with pytest.raises(CrankError) as ei:
        net.crank_to_quiescence()
    assert ei.value.report is not None
    assert "crank limit 10 exceeded" in str(ei.value)


def test_run_scenario_surfaces_stall_instead_of_raising():
    r = run_scenario("withhold_echo", "lossy", 4, seed=7, crank_limit=50_000)
    # lossy violates eventual delivery: whatever the seed does, the cell
    # must come back as a verdict, never an exception
    assert r.ok or (r.error is not None)


# ---------------------------------------------------------------------------
# Slow arms: the full acceptance matrix and the N=100/f=33 cell
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("attack", MATRIX_ATTACKS)
def test_full_matrix_n16(attack):
    for schedule in MATRIX_SCHEDULES:
        _cell_ok(run_scenario(attack, schedule, 16, seed=1))


@pytest.mark.slow
def test_matrix_n100_f33_arm():
    """The north-star width: N=100, f=33 crafted-share senders — every
    honest node still commits.  Uniform delivery: the schedule layer's
    per-message heap ops and rng draws would stretch an already
    ~16-minute cell further for no new coverage — network conditions at
    width are the N=16 matrix's job."""
    r = run_scenario(
        "crafted_shares", "uniform", 100, f=33, seed=1,
        crank_limit=50_000_000,
    )
    _cell_ok(r)
    assert r.f == 33
    assert r.fault_kinds.get("threshold_decrypt:invalid_share", 0) > 0


@pytest.mark.slow
def test_run_matrix_helper_full():
    # Seeds are pinned to ones where every expected fault lands: whether
    # a crafted share is VERIFIED (vs the decrypt terminating first on
    # threshold+1 honest shares) depends on delivery timing, so a cell's
    # expected-fault verdict is a deterministic function of its seed —
    # e.g. seed 4 lets every N=4 decrypt outrun the faulty sender under
    # the lan schedule.  Replay determinism makes any passing seed
    # stable forever.
    results = run_matrix(ns=(4, 7), epochs=1, seed=0)
    for r in results:
        _cell_ok(r)
