"""Integration tests: TpuBackend against the golden CpuBackend semantics.

Small N (the pure-Python golden side costs seconds per pairing), but the
full protocol-relevant surface: signature shares, full signatures,
decryption shares, ciphertext validity, and both combines — valid,
invalid and mixed batches, padding edge cases.
"""

import random

import pytest

from hbbft_tpu.crypto.keys import SignatureShare
from hbbft_tpu.ops.backend import TpuBackend


@pytest.fixture(scope="module")
def rng():
    return random.Random(2024)


@pytest.fixture(scope="module")
def backend():
    return TpuBackend()


@pytest.fixture(scope="module")
def keyset(backend, rng):
    sks = backend.generate_key_set(1, rng)  # threshold t=1: need 2 shares
    return sks, sks.public_keys()


@pytest.fixture(autouse=True)
def _restore_backend_tuning(backend):
    """Tests flip device_combine_threshold / device_lane_cap to force path
    selection; restore the pre-test values so the module-shared backend
    never leaks a tuned value into later tests (and in-test restores need
    not hardcode the class default)."""
    orig_threshold = backend.device_combine_threshold
    orig_cap = backend.device_lane_cap
    yield
    backend.device_combine_threshold = orig_threshold
    backend.device_lane_cap = orig_cap


def test_verify_sig_shares_mixed(backend, keyset, rng):
    sks, pks = keyset
    doc = b"epoch-0-coin"
    items = []
    want = []
    for i in range(3):
        share = sks.secret_key_share(i).sign_share(doc)
        items.append((pks.public_key_share(i), doc, share))
        want.append(True)
    # wrong share index (pk mismatch)
    share0 = sks.secret_key_share(0).sign_share(doc)
    items.append((pks.public_key_share(1), doc, share0))
    want.append(False)
    # wrong document
    share_bad = sks.secret_key_share(2).sign_share(b"other-doc")
    items.append((pks.public_key_share(2), doc, share_bad))
    want.append(False)
    assert backend.verify_sig_shares(items) == want


def test_combine_signatures_device_and_host(backend, keyset, rng):
    sks, pks = keyset
    doc = b"combine-me"
    shares = {i: sks.secret_key_share(i).sign_share(doc) for i in range(4)}
    # host path (below threshold count)
    backend.device_combine_threshold = 99
    sig_host = backend.combine_signatures(pks, shares)
    # device path
    backend.device_combine_threshold = 2
    sig_dev = backend.combine_signatures(pks, shares)
    assert sig_host == sig_dev
    assert pks.public_key().verify(sig_dev, doc)


def test_combine_signatures_reverify_falls_back(backend, keyset, monkeypatch):
    """A corrupted device combine must be caught by the master-PK re-verify
    and replaced by the host golden combine (ops/curve.py defense-in-depth
    claim)."""
    sks, pks = keyset
    doc = b"reverify-me"
    shares = {i: sks.secret_key_share(i).sign_share(doc) for i in range(4)}
    want = pks.combine_signatures(shares)

    # Sabotage the device ladder: return a valid-looking but wrong G2 point.
    wrong_point = backend.group.hash_to_g2(b"not the signature")
    monkeypatch.setattr(
        backend, "_lagrange_device_g2", lambda pts: wrong_point
    )
    backend.device_combine_threshold = 2
    sig = backend.combine_signatures(pks, shares, doc=doc)
    assert sig == want
    assert pks.public_key().verify(sig, doc)

    # Without the doc there is nothing to re-verify against: the corrupted
    # point passes through (documents why callers should pass doc).
    backend.device_combine_threshold = 2
    sig_noctx = backend.combine_signatures(pks, shares)
    assert sig_noctx.el == wrong_point


def test_threshold_decryption_roundtrip(backend, keyset, rng):
    sks, pks = keyset
    msg = b"the quick brown badger"
    ct = pks.encrypt(msg, rng)

    assert backend.verify_ciphertexts([ct]) == [True]

    items = []
    shares = {}
    for i in range(3):
        share = sks.secret_key_share(i).decrypt_share_unchecked(ct)
        shares[i] = share
        items.append((pks.public_key_share(i), ct, share))
    # tampered share
    bad = SignatureShare  # noqa: F841 (just for import liveness)
    wrong = sks.secret_key_share(0).decrypt_share_unchecked(ct)
    items.append((pks.public_key_share(2), ct, wrong))
    assert backend.verify_dec_shares(items) == [True, True, True, False]

    backend.device_combine_threshold = 2
    out_dev = backend.combine_decryption_shares(pks, shares, ct)
    backend.device_combine_threshold = 99
    out_host = backend.combine_decryption_shares(pks, shares, ct)
    assert out_dev == out_host == msg


def test_verify_signatures_full(backend, rng):
    sk = backend.generate_secret_key(rng)
    pk = sk.public_key()
    msg = b"vote: add node 7"
    sig = sk.sign(msg)
    other = backend.generate_secret_key(rng).sign(msg)
    got = backend.verify_signatures([(pk, msg, sig), (pk, msg, other)])
    assert got == [True, False]


def test_empty_batch(backend):
    assert backend.verify_sig_shares([]) == []
    assert backend.verify_ciphertexts([]) == []


def test_combine_dec_shares_batch_device_path(backend, keyset, rng):
    """The vmapped one-dispatch batch combine must match the scalar
    device combine and the host golden combine bit-for-bit."""
    sks, pks = keyset
    items = []
    msgs = []
    for j in range(3):
        msg = bytes([65 + j]) * 12
        ct = pks.encrypt(msg, rng)
        shares = {
            i: sks.secret_key_share(i).decrypt_share_unchecked(ct)
            for i in (0, 2)
        }
        items.append((shares, ct))
        msgs.append(msg)
    d0 = backend.counters.device_dispatches
    backend.device_combine_threshold = 2  # force the device batch path
    got = backend.combine_dec_shares_batch(pks, items)
    assert got == msgs
    assert backend.counters.device_dispatches == d0 + 1
    # generic loop (host golden) agrees
    host = [
        pks.combine_decryption_shares(shares, ct) for shares, ct in items
    ]
    assert host == msgs


def test_decrypt_shares_batch_device_path(backend, keyset, rng):
    """The batched G1 ladder share generation must match the host golden
    decrypt_share_unchecked bit-for-bit (and actually dispatch once)."""
    sks, pks = keyset
    items = []
    for j in range(3):
        ct = pks.encrypt(bytes([70 + j]) * 9, rng)
        for i in (0, 1, 2):
            items.append((sks.secret_key_share(i), ct))
    d0 = backend.counters.device_dispatches
    backend.device_combine_threshold = 2  # force the device path
    got = backend.decrypt_shares_batch(items)
    assert backend.counters.device_dispatches == d0 + 1
    want = [sk.decrypt_share_unchecked(ct) for sk, ct in items]
    assert [g.el for g in got] == [w.el for w in want]
    # and the shares actually decrypt: combine threshold+1 of them
    shares = {i: got[i] for i in (0, 2)}
    assert pks.combine_decryption_shares(shares, items[0][1]) == bytes([70]) * 9


def test_combine_dec_shares_batch_lane_capped_chunks(backend, keyset, rng):
    """A batch above device_lane_cap splits into several device chunks
    (the N=100 full-workload shape OOMed HBM in one graph); every chunk
    must still decrypt correctly and in order."""
    sks, pks = keyset
    items = []
    msgs = []
    for j in range(6):
        msg = bytes([80 + j]) * 10
        ct = pks.encrypt(msg, rng)
        shares = {
            i: sks.secret_key_share(i).decrypt_share_unchecked(ct)
            for i in (0, 2)
        }
        items.append((shares, ct))
        msgs.append(msg)
    d0 = backend.counters.device_dispatches
    backend.device_combine_threshold = 2
    # k=2 -> cap//k = 2 items, clamped UP to the _pad_bucket floor of 4
    # (a 2-item chunk would still pad to 4 items = 8 lanes; the floor
    # step dispatches the same 8 lanes with zero padding waste) ->
    # chunks of 4: [0:4], [4:6] = 2 dispatches
    backend.device_lane_cap = 4
    got = backend.combine_dec_shares_batch(pks, items)
    assert got == msgs
    assert backend.counters.device_dispatches == d0 + 2


def test_sign_shares_batch_device_path(backend, keyset):
    """Batched G2 coin-share generation must match the host golden
    sign_share bit-for-bit (and actually dispatch once)."""
    sks, pks = keyset
    items = []
    for j in range(3):
        doc = bytes([90 + j]) * 8
        for i in (0, 1, 2):
            items.append((sks.secret_key_share(i), doc))
    d0 = backend.counters.device_dispatches
    backend.device_combine_threshold = 2  # force the device path
    got = backend.sign_shares_batch(items)
    assert backend.counters.device_dispatches == d0 + 1
    want = [sk.sign_share(doc) for sk, doc in items]
    assert [g.el for g in got] == [w.el for w in want]
    # shares verify against their public key shares
    assert backend.verify_sig_shares(
        [(pks.public_key_share(i % 3), items[i][1], got[i]) for i in range(9)]
    ) == [True] * 9


def test_combine_sig_shares_batch_device_path(backend, keyset):
    """Batched G2 Lagrange combines over DIFFERENT share subsets must all
    produce the unique master signature (and match the host golden)."""
    sks, pks = keyset
    doc = b"batch-combine-sig"
    all_shares = {i: sks.secret_key_share(i).sign_share(doc) for i in range(4)}
    want = pks.combine_signatures({i: all_shares[i] for i in (0, 1)})
    items = [
        ({0: all_shares[0], 1: all_shares[1]}, doc),
        ({2: all_shares[2], 3: all_shares[3]}, None),
        ({1: all_shares[1], 3: all_shares[3]}, doc),
    ]
    backend.device_combine_threshold = 2  # force the device path
    got = backend.combine_sig_shares_batch(pks, items)
    assert all(s == want for s in got), "subset-independence violated"
    assert pks.public_key().verify(got[0], doc)


def test_combine_sig_shares_batch_reverify_falls_back(
    backend, keyset, monkeypatch
):
    """A corrupted device batch combine must be caught by the doc-carrying
    re-verify and replaced by the host golden combine."""
    sks, pks = keyset
    doc = b"batch-reverify"
    shares = {i: sks.secret_key_share(i).sign_share(doc) for i in range(2)}
    want = pks.combine_signatures(shares)
    wrong = backend.group.hash_to_g2(b"garbage point")
    monkeypatch.setattr(
        backend,
        "_combine_sig_chunk",
        lambda pk_set, items, idxs, k, out: out.__setitem__(
            idxs[0], type(want)(backend.group, wrong)
        ),
    )
    backend.device_combine_threshold = 2
    got = backend.combine_sig_shares_batch(pks, [(shares, doc)])
    assert got[0] == want  # fallback repaired it


def test_device_seconds_attributed_by_kind(backend, keyset):
    """Every device dispatch bills a kind split of device_seconds (round-4
    verdict task 7: the n16 epoch's device time was 90% unattributed) —
    sign ladders, grouped-RLC verifies, and combines each land in their
    own counter, and the kinds sum to the total."""
    sks, pks = keyset
    c = backend.counters
    kinds = [
        "pairing", "rlc_sig", "rlc_dec", "combine", "sign", "decrypt",
        "dkg", "encrypt",
    ]

    def split():
        return {k: getattr(c, f"device_seconds_{k}") for k in kinds}

    backend.device_combine_threshold = 2  # force device paths
    start_kinds = split()
    start_total = c.device_seconds
    doc = b"attribution-doc"
    items = [(sks.secret_key_share(i), doc) for i in range(3)]

    before = split()
    shares = backend.sign_shares_batch(items)
    after = split()
    assert after["sign"] > before["sign"]

    before = after
    assert backend.verify_sig_shares(
        [(pks.public_key_share(i), doc, shares[i]) for i in range(3)]
    ) == [True] * 3
    after = split()
    assert after["rlc_sig"] > before["rlc_sig"]

    before = after
    backend.combine_signatures(pks, {0: shares[0], 1: shares[1]})
    after = split()
    assert after["combine"] > before["combine"]

    # batched threshold encryption bills the encrypt bucket, not dkg
    import random as _random

    from hbbft_tpu.engine.dkg_batch import batched_encrypt

    g = backend.group
    rng2 = _random.Random(8)
    pk_el = g.g1_mul(rng2.randrange(1, g.r), g.g1())
    before = after
    backend.device_combine_threshold = 1  # force ladders onto the backend
    batched_encrypt(
        backend, [pk_el] * 3, [b"a1", b"b2", b"c3"], rng2, kind="encrypt"
    )
    after = split()
    assert after["encrypt"] > before["encrypt"]
    assert after["dkg"] == before["dkg"]

    # the kind split accounts for the total: every dispatch site passes a
    # kind, so over this test's operations the kind deltas must EQUAL the
    # device_seconds delta (an unkinded site would reopen the round-4
    # 90%-unattributed hole this exists to prevent)
    kind_delta = sum(after.values()) - sum(start_kinds.values())
    total_delta = c.device_seconds - start_total
    assert abs(kind_delta - total_delta) < 1e-6
