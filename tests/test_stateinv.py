"""Unit tests for the per-class mutable-state inventory (PR 17).

The snapshot rule family (tests/test_lint.py) exercises the rules
end-to-end; these tests pin the inventory substrate itself —
init-path computation, value-shape classification, hook-call
detection, env-declaration parsing — so a rule regression can be
bisected to "inventory wrong" vs "rule logic wrong" in one run.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from hbbft_tpu.analysis.dataflow import summarize_module
from hbbft_tpu.analysis.engine import LintProject, ModuleSource
from hbbft_tpu.analysis.stateinv import (
    class_body_defaults,
    init_path_methods,
    inventory_class,
    inventory_module,
    parse_env_attrs,
    state_module_paths,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def _inventory(src: str, path: str = "hbbft_tpu/net/x.py"):
    mod = ModuleSource(path, textwrap.dedent(src))
    return inventory_module(mod)


def _one(src: str):
    invs = _inventory(src)
    assert len(invs) == 1
    return invs[0]


# ---------------------------------------------------------------------------
# init-path computation
# ---------------------------------------------------------------------------


def test_init_only_vs_runtime_classification():
    inv = _one(
        """\
        class Node:
            def __init__(self):
                self.counters = {}
                self._wire()

            def _wire(self):
                self.links = []

            def on_deliver(self, msg):
                self.last = msg
        """
    )
    assert inv.attrs["counters"].init_only
    assert inv.attrs["links"].init_only  # helper reachable only from __init__
    assert not inv.attrs["last"].init_only
    assert [w.context for w in inv.attrs["last"].runtime_writes] == [
        "Node.on_deliver"
    ]


def test_helper_called_from_runtime_entry_is_not_init_path():
    inv = _one(
        """\
        class Node:
            def __init__(self):
                self._reset()

            def _reset(self):
                self.buf = []

            def crank(self):
                self._reset()
        """
    )
    # _reset has a non-init caller (crank), so its writes are runtime
    assert not inv.attrs["buf"].init_only


def test_no_caller_method_is_runtime_entry():
    mod = ModuleSource(
        "hbbft_tpu/net/x.py",
        textwrap.dedent(
            """\
            class Node:
                def __init__(self):
                    pass

                def _orphan(self):
                    self.x = 1
            """
        ),
    )
    summary = summarize_module(mod)
    cls = next(iter(summary.classes.values()))
    assert init_path_methods(cls) == {"__init__"}


def test_closure_writes_are_runtime_even_under_init():
    inv = _one(
        """\
        class Node:
            def __init__(self, pipe):
                def deliver(res):
                    self.last_res = res
                pipe.on_result = deliver
        """
    )
    assert not inv.attrs["last_res"].init_only


# ---------------------------------------------------------------------------
# value-shape classification
# ---------------------------------------------------------------------------


def test_value_kinds_lambda_def_bound_method_param_plain():
    inv = _one(
        """\
        class Node:
            def setup(self, on_commit):
                self.a = lambda x: x
                def helper(y):
                    return y
                self.b = helper
                self.c = self.crank
                self.d = on_commit
                self.e = 42

            def crank(self):
                pass
        """
    )
    kinds = {
        name: inv.attrs[name].writes[0].value for name in "abcde"
    }
    assert kinds == {
        "a": "lambda",
        "b": "def",
        "c": "bound-method",
        "d": "param",
        "e": "plain",
    }
    assert inv.attrs["d"].writes[0].params == ("on_commit",)
    assert inv.attrs["a"].writes[0].callable_kind == "lambda"
    assert inv.attrs["b"].writes[0].callable_kind == "nested function"
    assert inv.attrs["c"].writes[0].callable_kind == "bound method"
    assert inv.attrs["d"].writes[0].callable_kind is None


def test_param_derived_expression_still_param():
    inv = _one(
        """\
        class Node:
            def __init__(self, hooks):
                self.hooks = tuple(hooks)
        """
    )
    w = inv.attrs["hooks"].writes[0]
    assert w.value == "param"
    assert w.params == ("hooks",)


# ---------------------------------------------------------------------------
# hook-call detection
# ---------------------------------------------------------------------------


def test_direct_hook_call_detected_methods_excluded():
    inv = _one(
        """\
        class Node:
            def commit(self, batch):
                self.on_commit(batch)
                self.crank()

            def crank(self):
                pass
        """
    )
    assert "on_commit" in inv.hook_calls
    assert "crank" not in inv.hook_calls  # real method, not a hook


def test_iterated_hook_call_anchored_at_for_iter():
    inv = _one(
        """\
        class Node:
            def fire(self, entry):
                for fn in self.listeners:
                    fn(entry)
                for item in self.rows:
                    item.append(entry)
        """
    )
    assert inv.hook_calls == {"listeners": 3}  # rows: loopvar never called


# ---------------------------------------------------------------------------
# declarations, defaults, is_real
# ---------------------------------------------------------------------------


def test_parse_env_attrs_and_class_defaults():
    import ast

    tree = ast.parse(
        textwrap.dedent(
            """\
            class Node:
                tracer = None
                limit: int = 8
                bare: int
                _SNAPSHOT_ENV_ATTRS = ("tracer", "sink")
            """
        )
    )
    cls = tree.body[0]
    names, line = parse_env_attrs(cls)
    assert names == ("tracer", "sink")
    assert line == 5
    defaults = class_body_defaults(cls)
    assert "tracer" in defaults and "limit" in defaults
    assert "bare" not in defaults  # bare annotation is not a default


def test_is_real_distinguishes_dead_env_declaration():
    inv = _one(
        """\
        class Node:
            tracer = None
            _SNAPSHOT_ENV_ATTRS = ("tracer", "ghost")

            def crank(self):
                if self.tracer is not None:
                    self.tracer.span("x")
        """
    )
    assert inv.env_attrs == ("tracer", "ghost")
    assert inv.is_real("tracer")
    assert not inv.is_real("ghost")


# ---------------------------------------------------------------------------
# registry resolution and memoization
# ---------------------------------------------------------------------------


def test_state_module_paths_from_disk_and_from_loaded_module(tmp_path):
    # from disk (repo_root fallback — the unit-test path)
    reg = tmp_path / "hbbft_tpu" / "utils"
    reg.mkdir(parents=True)
    (reg / "snapshot.py").write_text(
        '_STATE_MODULES = ("hbbft_tpu.protocols.x", "hbbft_tpu.net.y")\n',
        encoding="utf-8",
    )
    project = LintProject(tmp_path, {})
    assert state_module_paths(project) == (
        "hbbft_tpu/protocols/x.py",
        "hbbft_tpu/net/y.py",
    )
    # from the loaded project module (the full-run path): the loaded
    # source wins over whatever is on disk
    mod = ModuleSource(
        "hbbft_tpu/utils/snapshot.py",
        '_STATE_MODULES = ("hbbft_tpu.core.z",)\n',
    )
    project2 = LintProject(tmp_path, {mod.path: mod})
    assert state_module_paths(project2) == ("hbbft_tpu/core/z.py",)
    # missing registry entirely: empty scope, rules no-op
    assert state_module_paths(LintProject(tmp_path / "nowhere", {})) == ()


def test_inventory_module_memoized_per_source():
    mod = ModuleSource(
        "hbbft_tpu/net/x.py",
        "class Node:\n    def __init__(self):\n        self.x = 1\n",
    )
    assert inventory_module(mod) is inventory_module(mod)


def test_real_registry_classes_inventory_clean():
    """Smoke: inventory every real _STATE_MODULES file — no crashes, and
    CrashManager's well-known attrs classify as expected."""
    project = LintProject(REPO_ROOT, {})
    paths = state_module_paths(project)
    assert len(paths) >= 30
    crash = None
    for rel in paths:
        p = REPO_ROOT / rel
        mod = ModuleSource(rel, p.read_text(encoding="utf-8"))
        for inv in inventory_module(mod):
            if rel.endswith("net/crash.py") and inv.name == "CrashManager":
                crash = inv
    assert crash is not None
    assert "restart_listeners" in crash.env_attrs
    assert crash.is_real("restart_listeners")
