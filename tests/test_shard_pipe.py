"""Per-device pipelined shard dispatch (PR 18) — JAX-free tier-1 arm.

Covers the :class:`ShardedDispatchPipeline` contract (deterministic
placement, global-submission-order default drain, per-device FIFO with
cross-device freedom under ``choose_shard``, per-device depth trim,
mesh-wide sync point), the MockBackend out-of-order shard resolution
through the engine (single-queue vs per-device A/B: bit-identical
batches), the shard explorer target's replay determinism, and the
heartbeat's shard-imbalance field.  The mesh-side kill-switch A/B at
lane-cap chunk boundaries lives in tests/test_mesh_backend.py (needs
the virtual 8-device mesh).
"""

from hbbft_tpu.analysis import schedules
from hbbft_tpu.analysis.schedules import ShardedMockBackend
from hbbft_tpu.obs import HealthReporter
from hbbft_tpu.parallel.shardpipe import (
    ShardedDispatchPipeline,
    placement_policy,
    shardpipe_enabled,
)


def _pipe(n_devices=3, depth=100):
    return ShardedDispatchPipeline(n_devices, depth_fn=lambda: depth)


def _submit(pipe, value, log, reserve=True):
    if reserve:
        pipe.reserve_device()
    return pipe.submit(
        lambda: value, fetch=None, kind=f"k{value}", items=1,
        on_result=log.append,
    )


# ---------------------------------------------------------------------------
# Pipeline semantics
# ---------------------------------------------------------------------------


def test_round_robin_placement_is_recorded_and_cyclic():
    pipe = _pipe(3)
    log = []
    for i in range(7):
        _submit(pipe, i, log)
    assert pipe.placements == [0, 1, 2, 0, 1, 2, 0]
    assert pipe.dev_dispatches == [3, 2, 2]
    assert len(pipe) == 7
    pipe.flush()
    assert len(pipe) == 0


def test_default_drain_resolves_in_global_submission_order():
    # submission order across device queues AND the base single queue —
    # byte-compatible with the single-queue FIFO (the kill-switch A/B's
    # delivery order)
    pipe = _pipe(3)
    log = []
    for i in range(5):
        _submit(pipe, i, log, reserve=(i != 2))  # 2 rides the base queue
    pipe.flush()
    assert log == [0, 1, 2, 3, 4]


def test_choose_shard_reorders_cross_device_fifo_per_device():
    pipe = _pipe(2)
    log = []
    for i in range(4):  # devices 0,1,0,1
        _submit(pipe, i, log)
    pipe.choose_shard = lambda ready: len(ready) - 1  # last ready first
    pipe.flush()
    # cross-device order flipped, per-device FIFO intact (1 before 3,
    # 0 before 2)
    assert log == [1, 3, 0, 2]


def test_depth_trims_per_device_not_globally():
    pipe = _pipe(2, depth=1)
    log = []
    _submit(pipe, 0, log)  # device 0
    _submit(pipe, 1, log)  # device 1 — its own queue, no trim of dev 0
    assert log == []
    _submit(pipe, 2, log)  # device 0 again: trims entry 0
    assert log == [0]
    pipe.flush()
    assert log == [0, 1, 2]


def test_sync_submit_drains_every_queue_in_program_order():
    pipe = _pipe(3)
    log = []
    for i in range(3):
        _submit(pipe, i, log)
    pipe.choose_shard = lambda ready: len(ready) - 1  # must NOT apply
    p = pipe.submit(lambda: "sync", fetch=None, on_result=log.append,
                    sync=True)
    assert p.done
    assert log == [0, 1, 2, "sync"]  # mesh-wide single sync point


def test_killswitch_and_placement_policy_env(monkeypatch):
    monkeypatch.delenv("HBBFT_TPU_NO_SHARD_PIPE", raising=False)
    assert shardpipe_enabled()
    monkeypatch.setenv("HBBFT_TPU_NO_SHARD_PIPE", "1")
    assert not shardpipe_enabled()
    monkeypatch.delenv("HBBFT_TPU_SHARD_PLACEMENT", raising=False)
    assert placement_policy() == "round_robin"
    monkeypatch.setenv("HBBFT_TPU_SHARD_PLACEMENT", "least_loaded")
    assert placement_policy() == "least_loaded"
    monkeypatch.setenv("HBBFT_TPU_SHARD_PLACEMENT", "bogus")
    assert placement_policy() == "round_robin"  # fall back, don't raise


def test_least_loaded_placement_reads_queue_depths(monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_SHARD_PLACEMENT", "least_loaded")
    pipe = _pipe(3)
    log = []
    for i in range(4):
        _submit(pipe, i, log)
    # empty queues tie to the lowest index, then depths equalize
    assert pipe.placements == [0, 1, 2, 0]
    pipe.flush()
    _submit(pipe, 9, log)
    assert pipe.placements[-1] == 0  # drained: all empty again


def test_imbalance_is_max_over_mean():
    pipe = _pipe(2)
    log = []
    for i in range(3):  # devices 0,1,0 → [2,1]
        _submit(pipe, i, log)
    assert abs(pipe.imbalance() - (2 / 1.5)) < 1e-9
    assert _pipe(2).imbalance() == 0.0  # no dispatches yet


# ---------------------------------------------------------------------------
# MockBackend out-of-order shard resolution (the tier-1 engine A/B)
# ---------------------------------------------------------------------------


def test_sharded_mock_delivers_out_of_submission_order():
    backend = ShardedMockBackend()
    backend.pipeline_chunk = 2
    order = []
    backend.chunk_listeners = (lambda lo, res: order.append(lo),)
    out, finish = backend._piped_submit(
        list(range(10)), lambda chunk: [x * 2 for x in chunk]
    )
    assert finish() is out
    # chunks at offsets 0,2,4,6 landed on devices 0..3 and offset 8 on
    # device 0; the default last-ready-first hook resolves cross-device
    # out of submission order while device 0 stays FIFO (0 before 8)
    assert order == [6, 4, 2, 0, 8]
    assert out == [x * 2 for x in range(10)]  # slot-disjoint: unharmed
    assert backend._pipe.placements == [0, 1, 2, 3, 0]


def test_engine_batches_bit_identical_single_queue_vs_sharded():
    """The conserved-output A/B at the engine level: the same seeded run
    through the single-queue MockBackend pipeline and through the
    per-device sharded pipeline (cross-device out-of-order) must commit
    bit-identical batches with identical fault logs and counters."""
    a = schedules.run_schedule("pipeline", 4, 11, [])
    b = schedules.run_schedule("shard", 4, 11, [])
    assert a.parts["batches_sha"] == b.parts["batches_sha"]
    assert a.parts["faults"] == b.parts["faults"]
    assert a.parts["counters"] == b.parts["counters"]
    assert a.parts["error"] == b.parts["error"] == ""
    # the sharded run really did spread whole chunks across devices
    assert len([d for d in b.parts["extra"]["dev_dispatches"] if d]) > 1


def test_shard_target_replay_is_deterministic():
    a = schedules.run_schedule("shard", 4, 5, [1, 0, 2])
    b = schedules.run_schedule("shard", 4, 5, [1, 0, 2])
    assert a.parts == b.parts
    assert a.parts["extra"]["placements_sha"] == \
        b.parts["extra"]["placements_sha"]
    assert a.canonical == b.canonical


def test_shard_tracker_orders_same_device_queue_entries():
    """RaceTracker devq footprints: same-device submit→resolve edges are
    ordered; cross-device resolves on the same batch surface as racing."""
    r = schedules.run_schedule("shard", 4, 0, [])
    devq = [e for e in r.events if any(k == "devq" for k, _ in e.writes)]
    assert devq, "no per-device-queue footprints recorded"
    kinds = {e.key.split(":", 1)[0] for e in devq}
    assert kinds == {"submit", "resolve"}


# ---------------------------------------------------------------------------
# Heartbeat field
# ---------------------------------------------------------------------------


def test_heartbeat_carries_shard_imbalance():
    beats = []
    hr = HealthReporter(
        interval_s=0.0,
        sink=beats.append,
        shard_stats_fn=lambda: {
            "shard_imbalance": 1.25,
            "shard_dispatches": [3, 1],
            "shard_devices": 2,
        },
    )
    rec = hr.tick(epoch=1, msgs=10.0)
    assert rec is not None
    assert rec["shard_imbalance"] == 1.25
    assert rec["shard_dispatches"] == [3, 1]
    # the hook must never break a heartbeat
    hr_bad = HealthReporter(
        interval_s=0.0, sink=beats.append,
        shard_stats_fn=lambda: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    rec2 = hr_bad.tick(epoch=2, msgs=20.0)
    assert rec2 is not None and "shard_imbalance" not in rec2
