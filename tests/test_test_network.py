"""Legacy TestNetwork compat harness (net/test_network.py) — the old
step-wise API must drive real consensus over the VirtualNet machinery."""

import dataclasses

import pytest

from hbbft_tpu.net.test_network import (
    FlipBoolAdversary,
    MessageScheduler,
    SilentAdversary,
    TestNetwork,
)
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement


def _ba(netinfo, backend):
    return BinaryAgreement(netinfo, backend, session_id=b"legacy")


@pytest.mark.parametrize("sched", [MessageScheduler.RANDOM, MessageScheduler.FIRST])
def test_ba_agreement_both_schedulers(sched):
    net = TestNetwork(4, 0, _ba, scheduler=sched, seed=11)
    for i in range(4):
        net.input(i, i % 2 == 0)
    outs = net.run()
    assert len(outs) == 4
    vals = {tuple(v) for v in outs.values()}
    assert len(vals) == 1 and all(len(v) == 1 for v in outs.values())


def test_stepwise_api_delivers_one_message_per_step():
    net = TestNetwork(4, 0, _ba, scheduler=MessageScheduler.FIRST, seed=3)
    net.input_all(True)
    before = net.net.messages_delivered
    got = net.step()
    assert got is not None and net.net.messages_delivered == before + 1
    outs = net.run()
    assert {tuple(v) for v in outs.values()} == {(True,)}


def test_silent_adversary_crash_faults_tolerated():
    net = TestNetwork(6, 1, _ba, adversary=SilentAdversary(), seed=5)
    net.input_all(True)
    outs = net.run()
    # correct nodes decide despite the crashed (silent) faulty node
    assert len(outs) == 6
    assert {tuple(v) for v in outs.values()} == {(True,)}


def test_flip_bool_adversary_payload_flip():
    adv = FlipBoolAdversary()

    @dataclasses.dataclass(frozen=True)
    class Inner:
        b: bool
        n: int

    @dataclasses.dataclass(frozen=True)
    class Msg:
        kind: str
        flag: bool
        inner: Inner

    flipped = adv._flip_payload(Msg("x", True, Inner(False, 3)))
    assert flipped.flag is False and flipped.inner.b is True
    assert flipped.kind == "x" and flipped.inner.n == 3
    # non-dataclass payloads pass through untouched
    raw = object()
    assert adv._flip_payload(raw) is raw


def test_flip_bool_adversary_end_to_end():
    net = TestNetwork(6, 1, _ba, adversary=FlipBoolAdversary(), seed=9)
    net.input_all(False)
    outs = net.run()
    assert len(outs) == 6
    assert {tuple(v) for v in outs.values()} == {(False,)}
