"""Static attribution guard for the backend dispatch seam.

Every jitted device dispatch must carry a ``device_seconds_*`` kind label
— otherwise epoch device time silently regresses to "unkinded" and the
per-kind breakdown on the macro bench rows (round-4 verdict task 7) and
the trace's dispatch-span categories both lose attribution.  This test
introspects the AST of ``hbbft_tpu/ops/backend.py`` and fails on any
call into the seam that omits ``kind=`` or names a kind with no matching
Counters field.
"""

import ast
import dataclasses
import inspect

import hbbft_tpu.ops.backend as backend_mod
from hbbft_tpu.utils.metrics import Counters

#: seam functions whose ``kind`` parameter defaults to "" (unkinded):
#: every CALL must therefore pass kind= explicitly (_dispatch_async is
#: the pipelined deferred-fetch twin of _dispatch_fetch)
_SEAM_FNS = (
    "_dispatch_fetch", "_dispatch_async", "_ladder_batch", "_grouped_rlc",
)


def _counters_kinds():
    return {
        f.name[len("device_seconds_"):]
        for f in dataclasses.fields(Counters)
        if f.name.startswith("device_seconds_")
    }


def _seam_calls(tree):
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _SEAM_FNS
        ):
            yield node


def test_every_dispatch_path_carries_a_kind_label():
    tree = ast.parse(inspect.getsource(backend_mod))
    valid = _counters_kinds()
    assert valid, "Counters lost its device_seconds_* split"
    problems = []
    for call in _seam_calls(tree):
        kws = {k.arg: k.value for k in call.keywords}
        if "kind" not in kws:
            problems.append(
                f"ops/backend.py:{call.lineno}: {call.func.attr}(...) "
                "without kind= — dispatch would be unkinded"
            )
            continue
        v = kws["kind"]
        if isinstance(v, ast.Constant):
            if not (isinstance(v.value, str) and v.value):
                problems.append(
                    f"ops/backend.py:{call.lineno}: empty kind literal"
                )
            elif v.value not in valid:
                problems.append(
                    f"ops/backend.py:{call.lineno}: kind {v.value!r} has no "
                    f"Counters.device_seconds_{v.value} field"
                )
        # a Name (kind=kind) forwards the caller's label; the caller's own
        # call site is checked by this same loop
    assert not problems, "\n".join(problems)


def test_seam_calls_are_actually_present():
    # the guard is vacuous if a refactor renames the seam — pin the shape
    tree = ast.parse(inspect.getsource(backend_mod))
    names = [c.func.attr for c in _seam_calls(tree)]
    # sync + deferred dispatch sites together carry every device call
    assert names.count("_dispatch_fetch") + names.count("_dispatch_async") >= 4
    assert names.count("_dispatch_async") >= 3  # the pipelined chunk seams
    assert "_grouped_rlc" in names and "_ladder_batch" in names


def test_public_batch_entry_points_have_kinded_defaults():
    """g1_mul_batch/g2_mul_batch are called kind-less by the batched DKG —
    their DEFAULT must itself be a valid kind, not ''."""
    valid = _counters_kinds()
    for fn_name in ("g1_mul_batch", "g2_mul_batch"):
        fn = getattr(backend_mod.TpuBackend, fn_name)
        default = inspect.signature(fn).parameters["kind"].default
        assert default in valid, (fn_name, default)


def test_glv_ab_bench_kind_registered():
    """bench.py's glv_ladder_ab row dispatches its A/B ladders under
    kind="glv_ab" (through g1_mul_batch) so the row's device time is
    attributable separately from real DKG work — the kind must exist as
    a Counters field or the dispatch would be unkinded."""
    assert "glv_ab" in _counters_kinds()


def test_fused_chain_kind_registered():
    """Verification dispatches routed onto the VMEM-resident fused tower
    chain (PR 20) bill under kind="fused_chain" so the fused/unfused A/B
    reads directly off the per-kind device-seconds split — the kind must
    exist as a Counters field or those dispatches would be unkinded."""
    assert "fused_chain" in _counters_kinds()


def test_device_rs_plane_kinds_registered():
    """The device erasure/hash plane (PR 19) dispatches RS encode,
    RS decode, and Merkle build/verify chunks under their own kinds so
    the folded host buckets reappear attributed inside device_seconds —
    every kind must exist as a Counters field."""
    assert {"rs_enc", "rs_dec", "merkle"} <= _counters_kinds()
