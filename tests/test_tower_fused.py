"""Fused VMEM-resident tower chain (PR 20): goldens, routing, kill switch.

Every fused kernel reuses the exact recombination code of ops/tower.py on
top of the `fq_rns_pallas` Montgomery core, so the acceptance bar is
BIT-IDENTICAL represented values (canonical readback via
``tower.*_to_ints``), not approximate agreement:

* interpret-mode op goldens — fq2/fq6/fq12 mul+sqr and the cyclotomic
  square against the stacked tower ops on the same inputs;
* the fused Miller loop and the whole fused verification graph
  (`product2_fast_fused`) against `pairing.product2_fast`, including the
  degenerate infinity-lane arm (mirroring test_glv_degenerate's adversarial
  route probes);
* the backend kill-switch A/B: HBBFT_TPU_NO_FUSED_TOWER must restore the
  unfused graphs exactly — identical verdicts, identical
  ``device_dispatches``, and counter non-leak in BOTH directions;
* the analytic dispatch model: ≥3× fewer Pallas launches per verification
  graph (the ISSUE 20 acceptance bar).

All kernels run with TILE=8 in interpret mode (no Mosaic on CPU); the
lru-cached pallas_call factories key on (tile, interpret) so the patched
tile never leaks into other modules.
"""

import os
import random

import numpy as np
import pytest

import jax.numpy as jnp

from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq, pairing, tower

pytestmark = pytest.mark.skipif(
    fq.IMPL != "rns", reason="fused tower kernels bind to the RNS field impl"
)

import hbbft_tpu.ops.pairing_chain as pc  # noqa: E402
import hbbft_tpu.ops.tower_fused as tf  # noqa: E402


@pytest.fixture(autouse=True)
def _small_tile(monkeypatch):
    monkeypatch.setattr(tf, "TILE", 8)


@pytest.fixture(scope="module")
def rng():
    return random.Random(2020)


def _rnd_fq2(rng):
    return (rng.randrange(Q), rng.randrange(Q))


def _rnd_fq6(rng):
    return tuple(_rnd_fq2(rng) for _ in range(3))


def _rnd_fq12(rng):
    return tuple(_rnd_fq6(rng) for _ in range(2))


def test_fused_ops_bit_identical_to_stacked_tower(rng):
    """fq2/fq6/fq12 mul+sqr and cyclo-sqr: the single-kernel fused ops
    must reproduce the stacked tower ops bit-for-bit on canonical
    readback (same recombination code, same Montgomery core)."""
    n = 8
    x2 = [_rnd_fq2(rng) for _ in range(n)]
    y2 = [_rnd_fq2(rng) for _ in range(n)]
    a2, b2 = tower.fq2_stack(x2), tower.fq2_stack(y2)
    got = tf.fq2_mul(a2, b2, interpret=True)
    want = tower.fq2_mul(a2, b2)
    for i in range(n):
        assert tower.fq2_to_ints(got, i) == tower.fq2_to_ints(want, i)
    got = tf.fq2_sqr(a2, interpret=True)
    want = tower.fq2_sqr(a2)
    for i in range(n):
        assert tower.fq2_to_ints(got, i) == tower.fq2_to_ints(want, i)

    x6 = [_rnd_fq6(rng) for _ in range(n)]
    y6 = [_rnd_fq6(rng) for _ in range(n)]
    a6, b6 = tower.fq6_stack(x6), tower.fq6_stack(y6)
    got = tf.fq6_mul(a6, b6, interpret=True)
    want = tower.fq6_mul(a6, b6)
    for i in range(n):
        assert tower.fq6_to_ints(got, i) == tower.fq6_to_ints(want, i)
    got = tf.fq6_sqr(a6, interpret=True)
    want = tower.fq6_sqr(a6)
    for i in range(n):
        assert tower.fq6_to_ints(got, i) == tower.fq6_to_ints(want, i)

    x12 = [_rnd_fq12(rng) for _ in range(n)]
    y12 = [_rnd_fq12(rng) for _ in range(n)]
    a12, b12 = tower.fq12_stack(x12), tower.fq12_stack(y12)
    for fused_fn, stacked_fn, args in (
        (tf.fq12_mul, tower.fq12_mul, (a12, b12)),
        (tf.fq12_sqr, tower.fq12_sqr, (a12,)),
        (tf.fq12_cyclo_sqr, tower.fq12_cyclo_sqr, (a12,)),
    ):
        got = fused_fn(*args, interpret=True)
        want = stacked_fn(*args)
        for i in range(n):
            assert tower.fq12_to_ints(got, i) == tower.fq12_to_ints(want, i)


def test_fused_miller_loop_bit_identical(rng):
    n = 2
    P1, Q1, _, _ = pairing.example_verify_batch(n, seed=5, distinct=n)
    got = pc.miller_loop_fused(P1, Q1, mode="interpret")
    want = pairing.miller_loop(P1, Q1)
    assert tower.fq12_to_ints_batch(got, n) == tower.fq12_to_ints_batch(want, n)


def test_fused_product2_bit_identical_and_verdicts(rng):
    """The whole fused verification graph (merged Miller + fused hard
    part) against the stacked graph, plus the pairing verdicts the
    backend actually consumes — and the analytic ≥3× dispatch drop."""
    n = 2
    P1, Q1, P2, Q2 = pairing.example_verify_batch(n, seed=0, distinct=n)
    got = pc.product2_fast_fused(P1, Q1, P2, Q2, mode="interpret")
    want = pairing.product2_fast(P1, Q1, P2, Q2)
    assert tower.fq12_to_ints_batch(got, n) == tower.fq12_to_ints_batch(want, n)
    assert all(pairing.is_one_host_batch(got, n))
    # the routed entry point reaches the same graph
    via_route = pairing.product2_fast(P1, Q1, P2, Q2, fused="interpret")
    assert tower.fq12_to_ints_batch(via_route, n) == tower.fq12_to_ints_batch(
        want, n
    )
    ratio = pc.analytic_pallas_calls(2, fused=False) / pc.analytic_pallas_calls(
        2, fused=True
    )
    assert ratio >= 3.0, f"fused chain saves only {ratio:.2f}x launches"


def test_fused_product2_degenerate_infinity_lanes(rng):
    """Infinity lanes (mirroring test_glv_degenerate's adversarial route
    probes): the fused graph must route the neutral-select exactly like
    the stacked one — a lane with P or Q at infinity contributes the
    identity, wherever the infinity flag lands.  Deliberately the SAME
    n=2 batch shape as the golden test above: the degenerate arm rides
    the already-compiled graphs (compile-budget discipline, PERF.md
    round 16) — only the infinity flags differ."""
    n = 2
    P1, Q1, P2, Q2 = pairing.example_verify_batch(n, seed=0, distinct=n)

    def with_inf(T, lanes):
        x, y, inf = T
        mask = np.zeros(np.shape(inf), dtype=bool)
        for i in lanes:
            mask[i] = True
        return (x, y, jnp.asarray(np.asarray(inf) | mask))

    P1d = with_inf(P1, [0])  # pair-1 P at infinity on lane 0
    Q2d = with_inf(Q2, [0])  # pair-2 Q at infinity on lane 0
    got = pc.product2_fast_fused(P1d, Q1, P2, Q2d, mode="interpret")
    want = pairing.product2_fast(P1d, Q1, P2, Q2d)
    assert tower.fq12_to_ints_batch(got, n) == tower.fq12_to_ints_batch(want, n)
    # lane 1 is an untouched valid verification lane → still one; lane 0
    # degenerates BOTH pairs to the identity, so the product is one too
    assert all(pairing.is_one_host_batch(got, n))


def _backend_arm(monkeypatch, kill: bool):
    from hbbft_tpu.ops.backend import TpuBackend

    monkeypatch.setenv("HBBFT_TPU_FUSED_TOWER", "interpret")
    if kill:
        monkeypatch.setenv("HBBFT_TPU_NO_FUSED_TOWER", "1")
    else:
        monkeypatch.delenv("HBBFT_TPU_NO_FUSED_TOWER", raising=False)
    rng = random.Random(2024)
    be = TpuBackend()
    sks = be.generate_key_set(1, rng)
    pks = sks.public_keys()
    doc = b"pr20-fused-ab"
    items = []
    for i in range(3):
        items.append((pks.public_key_share(i), doc, sks.secret_key_share(i).sign_share(doc)))
    # one invalid item: pk/share index mismatch
    items.append((pks.public_key_share(1), doc, sks.secret_key_share(0).sign_share(doc)))
    verdicts = be.verify_sig_shares(items)
    return verdicts, be.counters.snapshot()


@pytest.mark.slow
def test_backend_kill_switch_ab(monkeypatch):
    """HBBFT_TPU_NO_FUSED_TOWER restores the unfused graphs exactly:
    identical verdicts, identical device_dispatches, and counter
    non-leak in BOTH directions (fused counters stay zero under the kill
    switch; the stacked launch counter stays zero on the fused arm).
    Slow: two full rlc_sig graph compiles (fused + stacked) on XLA:CPU."""
    fused_v, fused_c = _backend_arm(monkeypatch, kill=False)
    kill_v, kill_c = _backend_arm(monkeypatch, kill=True)

    assert fused_v == kill_v == [True, True, True, False]
    assert fused_c["device_dispatches"] == kill_c["device_dispatches"]

    assert fused_c["fused_tower_calls"] > 0
    assert fused_c["fused_chain_pallas_calls"] > 0
    assert fused_c["fused_chain_field_muls"] > 0
    # this small batch rides exact pairing checks → kind "fused_chain"
    assert fused_c["device_seconds_fused_chain"] > 0.0
    assert fused_c["stacked_chain_pallas_calls"] == 0

    assert kill_c["fused_tower_calls"] == 0
    assert kill_c["fused_chain_pallas_calls"] == 0
    assert kill_c["fused_chain_field_muls"] == 0
    assert kill_c["device_seconds_fused_chain"] == 0.0
    assert kill_c["stacked_chain_pallas_calls"] > 0


def test_mode_ladder_and_kill_switch_env(monkeypatch):
    """fused_tower_mode honours every rung of the fallback ladder."""
    for var in (
        "HBBFT_TPU_NO_PALLAS",
        "HBBFT_TPU_NO_FUSED",
        "HBBFT_TPU_NO_FUSED_TOWER",
        "HBBFT_TPU_FUSED_TOWER",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("HBBFT_TPU_FUSED_TOWER", "interpret")
    assert tf.fused_tower_mode() == "interpret"
    monkeypatch.setenv("HBBFT_TPU_NO_FUSED_TOWER", "1")
    assert tf.fused_tower_mode() is None  # per-call kill switch wins
    monkeypatch.delenv("HBBFT_TPU_NO_FUSED_TOWER", raising=False)
    for ladder_var in ("HBBFT_TPU_NO_FUSED", "HBBFT_TPU_NO_PALLAS"):
        monkeypatch.setenv(ladder_var, "1")
        assert tf.fused_tower_mode() is None  # inherited fallback rungs
        monkeypatch.delenv(ladder_var, raising=False)
    monkeypatch.setenv("HBBFT_TPU_FUSED_TOWER", "0")
    assert tf.fused_tower_mode() is None
    # resolve_mode: explicit override beats the env ladder
    monkeypatch.setenv("HBBFT_TPU_FUSED_TOWER", "interpret")
    assert pc.resolve_mode(False) is None
    assert pc.resolve_mode("native") == "native"
    assert pc.resolve_mode(None) == "interpret"


@pytest.mark.slow
def test_n16_engine_ab_batches_identical(monkeypatch):
    """N=16 real-crypto engine epoch, fused arm vs kill-switch arm:
    Batches bit-identical, device_dispatches identical, fused counters
    light up only on the fused arm (the ISSUE 20 engine-level A/B)."""
    from hbbft_tpu.engine import ArrayHoneyBadgerNet
    from hbbft_tpu.ops.backend import TpuBackend

    def arm(kill):
        monkeypatch.setenv("HBBFT_TPU_FUSED_TOWER", "interpret")
        if kill:
            monkeypatch.setenv("HBBFT_TPU_NO_FUSED_TOWER", "1")
        else:
            monkeypatch.delenv("HBBFT_TPU_NO_FUSED_TOWER", raising=False)
        be = TpuBackend()
        net = ArrayHoneyBadgerNet(range(16), backend=be, seed=0, coin_rounds=1)
        batches = net.run_epochs(1, payload_size=64)
        return batches, be.counters.snapshot()

    fused_b, fused_c = arm(False)
    kill_b, kill_c = arm(True)
    assert fused_b == kill_b, "fused chain changed Batch outputs"
    assert fused_c["device_dispatches"] == kill_c["device_dispatches"]
    assert fused_c["fused_tower_calls"] > 0
    assert kill_c["fused_tower_calls"] == 0
    assert kill_c["fused_chain_pallas_calls"] == 0
    assert fused_c["stacked_chain_pallas_calls"] == 0
