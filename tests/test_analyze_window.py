"""The window-snapshot analyzer must stay loadable and correct on the
snapshot format the runbook writes (it is the round-6 judge/EDA path
over the window artifacts)."""

import json
import subprocess
import sys
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_analyzer_over_synthetic_snapshots(tmp_path):
    (tmp_path / "rows_after_matrix_rns_a.json").write_text(json.dumps({
        "meta": {"fq_impl": "rns"},
        "rows": [{"metric": "rlc_dec_verify_throughput", "value": 17740.8,
                  "unit": "shares/s", "fq_impl": "rns", "row_seconds": 72.2}],
    }))
    (tmp_path / "rows_after_n100.json").write_text(json.dumps({
        "meta": {},
        "rows": [{"metric": "array_epochs_per_sec_n100", "value": 0.00464,
                  "unit": "epochs/s", "n": 100, "epochs": 10,
                  "device_seconds_per_epoch": 94.89,
                  "device_seconds_rlc_dec_per_epoch": 55.57,
                  "hash_g2_seconds_per_epoch": 1.5}],
    }))
    (tmp_path / "rows_after_broken.json").write_text(json.dumps({
        "meta": {},
        "rows": [{"metric": "coin_e2e", "error": "boom"}],
    }))
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "analyze_window.py"),
         str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    out = proc.stdout
    assert "17740.8" in out          # step table value
    assert "FAILED: boom" in out     # error row surfaced
    assert "rns_a" in out            # matrix column
    assert "rlc_dec" in out and "55.57" in out  # attribution kinds
