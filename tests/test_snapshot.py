"""Checkpoint/resume (utils/snapshot.py): a saved node — or a whole
VirtualNet — restores to an equivalent object that continues the protocol
deterministically (SURVEY.md §5 checkpoint row)."""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadgerBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign
from hbbft_tpu.utils.snapshot import SnapshotError, load_node, save_node


def _ts_net(seed=3):
    return (
        NetBuilder(range(4))
        .backend(MockBackend())
        .using(lambda ni, b: ThresholdSign(ni, b, doc=b"snapshot me"))
        .build(seed=seed)
    )


def test_threshold_sign_roundtrip_mid_protocol():
    net = _ts_net()
    net.broadcast_input(None)
    for _ in range(3):  # deliver a few shares, then checkpoint
        net.crank()
    # Snapshot a node that hasn't yet terminated (mock crypto needs only
    # f+1=2 shares, so early-cranked nodes finish fast).
    nid = next(
        n for n in net.nodes if not net.nodes[n].outputs
        and any(m.to == n for m in net.queue)
    )
    algo = net.nodes[nid].algorithm
    blob = save_node(algo)
    assert isinstance(blob, bytes) and len(blob) > 16

    restored = load_node(blob, net.backend)
    assert type(restored) is ThresholdSign
    assert restored.netinfo.our_id == algo.netinfo.our_id
    # Same pending-share state: feeding the identical remaining messages to
    # both must produce the identical unique threshold signature.
    def drain(step, backend, sink):
        """Eagerly resolve deferred CryptoWork (what VirtualNet does)."""
        sink.extend(step.output)
        for w in step.work:
            fn = {
                "verify_sig_share": backend.verify_sig_shares,
                "verify_signature": backend.verify_signatures,
            }[w.kind]
            drain(w.on_result(fn([w.payload])[0]), backend, sink)

    remaining = [m for m in net.queue if m.to == nid]
    outs_a, outs_b = [], []
    for m in remaining:
        drain(
            algo.handle_message(m.sender, m.payload),
            net.backend,
            outs_a,
        )
        drain(restored.handle_message(m.sender, m.payload), net.backend, outs_b)
    assert outs_a and outs_a == outs_b


def test_mid_epoch_snapshot_between_rbc_output_and_ba_decision():
    """A checkpoint taken strictly mid-epoch — after at least one RBC
    instance delivered its value but before its BA instance decided —
    restores to a node that still decides the identical Batch.  (The
    quiescent-state coverage elsewhere in this file never exercised the
    live Subset/BA sub-protocol state; the crash axis, net/crash.py,
    checkpoints at arbitrary crank boundaries, so this state must
    round-trip.)"""
    from hbbft_tpu.protocols.honey_badger import HoneyBadger

    def build(seed):
        return (
            NetBuilder(range(4))
            .backend(MockBackend())
            .scheduler("first")  # deterministic delivery without rng draws
            .using(lambda ni, be: HoneyBadger(ni, be, session_id=b"midsnap"))
            .build(seed=seed)
        )

    def mid_epoch_node(net):
        """A node with an RBC value delivered but that BA undecided."""
        for nid in sorted(net.nodes):
            es = net.nodes[nid].algorithm._epoch_state
            for ps in es.subset.proposals.values():
                if ps.value is not None and ps.decision is None:
                    return nid
        return None

    # Run A: uninterrupted reference.
    ref = build(seed=4)
    for i in sorted(ref.nodes):
        ref.send_input(i, {"from": i})
    ref.crank_until(
        lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
    )

    # Run B: same seed; at the first mid-epoch point, snapshot the node
    # and REPLACE it with the restored copy, then finish the epoch.
    net = build(seed=4)
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    target = None
    for _ in range(200_000):
        target = mid_epoch_node(net)
        if target is not None:
            break
        assert net.crank() is not None, "quiesced before a mid-epoch point"
    assert target is not None
    algo = net.nodes[target].algorithm
    es = algo._epoch_state
    assert any(
        ps.value is not None and ps.decision is None
        for ps in es.subset.proposals.values()
    )
    restored = load_node(save_node(algo), net.backend)
    # the restored instance must carry the live sub-protocol state
    res_es = restored._epoch_state
    assert any(
        ps.value is not None and ps.decision is None
        for ps in res_es.subset.proposals.values()
    )
    net.nodes[target].algorithm = restored
    net.crank_until(
        lambda nt: all(len(nd.outputs) >= 1 for nd in nt.correct_nodes())
    )
    # identical Batch on the restored node, its peers, and the reference
    batch = net.nodes[target].outputs[0]
    for nid in net.nodes:
        assert net.nodes[nid].outputs[0] == batch
    for nid in ref.nodes:
        assert ref.nodes[nid].outputs[0] == batch


def test_whole_network_resume_is_deterministic():
    """Snapshot an entire mid-epoch QHB network; the restored net and the
    original must produce identical outputs from identical futures."""

    def build():
        def make(ni, b, rng):
            return (
                QueueingHoneyBadgerBuilder(ni, b, rng)
                .batch_size(3)
                .build()
            )

        return (
            NetBuilder(range(4))
            .backend(MockBackend())
            .using(make)
            .build(seed=11)
        )

    net = build()
    for i in range(4):
        for t in range(5):
            net.send_input(i, ("user", ("tx", i, t)))
    for _ in range(120):  # mid-epoch checkpoint point
        net.crank()
    blob = save_node(net)

    net2 = load_node(blob, MockBackend())
    assert sorted(net2.nodes) == sorted(net.nodes)
    assert len(net2.queue) == len(net.queue)

    # Both nets now evolve independently but identically: same shared-RNG
    # state, same queues, same per-node protocol state.  (QHB proposes
    # forever, so compare a fixed horizon rather than quiescence.)
    for _ in range(3000):
        a, b = net.crank(), net2.crank()
        if a is None and b is None:
            break
        assert (a is None) == (b is None)
    assert net.cranks == net2.cranks
    assert len(net.queue) == len(net2.queue)
    progressed = False
    for nid in net.nodes:
        a, b = net.nodes[nid].outputs, net2.nodes[nid].outputs
        assert a == b
        progressed = progressed or bool(a)
    assert progressed, "network made no progress after resume"


def test_shared_rng_is_shared_after_restore():
    net = _ts_net(seed=9)
    blob = save_node(net)
    net2 = load_node(blob, MockBackend())
    assert net2.rng.getstate() == net.rng.getstate()


def test_generic_slotted_dataclasses_roundtrip():
    """core.types dataclasses are @dataclass(slots=True) + Generic[...]
    (typing.Generic contributes no __slots__ entry); they must serialize
    via the slots chain, not crash on a missing __dict__."""
    from hbbft_tpu.core.types import Step, Target, TargetedMessage

    tm = TargetedMessage(Target.node(1), ("msg", b"payload"))
    step = Step(messages=[tm], output=[("out", 7)])
    blob = save_node(step)
    back = load_node(blob, MockBackend())
    assert back.messages[0].message == tm.message
    assert back.messages[0].target == tm.target
    assert back.output == step.output


def test_set_members_with_shared_refs_roundtrip():
    """A set member referencing a memoized sibling must decode: member
    ordering is fixed before encoding so no ("r", idx) precedes its
    definition."""

    class Holder:  # stand-in for any registered class
        pass

    from hbbft_tpu.utils import snapshot as snap

    tag = f"{Holder.__module__}:{Holder.__qualname__}"
    snap._registry()[tag] = Holder
    try:
        fs = frozenset({1, 2})
        h = Holder()
        h.state = {fs, (fs,)}  # tuple member shares the frozenset
        back = load_node(save_node(h), MockBackend())
        assert back.state == h.state
    finally:
        snap._registry().pop(tag, None)


def test_callable_in_state_is_rejected():
    class Holder:
        pass

    h = Holder()
    h.cb = lambda: None
    with pytest.raises(SnapshotError):
        save_node(h)


def test_unregistered_class_is_rejected_on_decode():
    from hbbft_tpu.utils import canonical
    from hbbft_tpu.utils.snapshot import _MAGIC

    evil = _MAGIC + canonical.encode(("o", 0, "os:system", []))
    with pytest.raises(SnapshotError):
        load_node(evil, MockBackend())


def test_snapshot_is_canonical_bytes_no_pickle():
    net = _ts_net(seed=1)
    blob = save_node(net)
    # pickle streams start with \x80; ours starts with a fixed magic.
    assert blob.startswith(b"HBTPUSNAP1")
    # Same state → same bytes (canonical encoding is deterministic).
    assert blob == save_node(net)


def test_simulation_checkpoint_resume_matches_uninterrupted():
    """examples/simulation.py: run 2 epochs + checkpoint + resume to 4 must
    commit the same batches as an uninterrupted 4-epoch run."""
    import sys, os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples.simulation import Simulation

    class A:
        num_nodes = 4
        num_faulty = 1
        batch_size = 3
        tx_size = 8
        txns = 12
        epochs = 4
        lam = 10.0
        bandwidth = 2000.0
        cpu_factor = 1.0
        crypto_window = 64
        seed = 7

    # Uninterrupted run.
    full = Simulation(A, MockBackend(), random.Random(0))
    full.run()

    # Interrupted at 2 epochs, checkpointed, resumed in a FRESH Simulation.
    class A2(A):
        epochs = 2

    first = Simulation(A2, MockBackend(), random.Random(0))
    first.run()
    blob = first.checkpoint()

    second = Simulation(A, MockBackend(), random.Random(99))  # rng replaced
    second.restore(blob)
    rows = second.run()
    assert rows and rows[0]["epoch"] >= 2  # only new epochs reported

    for nid in full.nodes:
        a = [b.contributions for b in full.nodes[nid].outputs[:4]]
        b = [b.contributions for b in second.nodes[nid].outputs[:4]]
        assert a == b


def test_simulation_checkpoint_before_first_epoch_does_not_reseed():
    """A checkpoint written before any epoch completes must not cause the
    resumed run to re-seed (and thus duplicate) the transaction queues."""
    import sys, os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from examples.simulation import Simulation

    class A:
        num_nodes = 4
        num_faulty = 1
        batch_size = 3
        tx_size = 8
        txns = 5
        epochs = 0  # stop before the first epoch
        lam = 10.0
        bandwidth = 2000.0
        cpu_factor = 1.0
        crypto_window = 64
        seed = 7

    first = Simulation(A, MockBackend(), random.Random(0))
    first.run()
    blob = first.checkpoint()

    class A2(A):
        epochs = 2

    resumed = Simulation.from_checkpoint(A2, MockBackend(), blob)
    resumed.run()
    for node in resumed.nodes.values():
        # 5 unique txs per node seeded once; duplicates would double this.
        assert len(node.algo.algo.queue) <= A.txns * A.num_nodes


def test_malformed_snapshot_raises_snapshot_error():
    from hbbft_tpu.utils import canonical
    from hbbft_tpu.utils.snapshot import _MAGIC

    # Corrupted rng payload (setstate would TypeError), truncated bytes,
    # and garbage trees must all surface as SnapshotError.
    bad = [
        _MAGIC + canonical.encode(("rng", 0, 99, [1, 2], ("p", None))),
        _MAGIC + canonical.encode(("nd", 0, "<f4", [5, 5], b"xx")),
        _MAGIC + b"\xff\xff",
        save_node([1, 2, 3])[:-3],
    ]
    for blob in bad:
        with pytest.raises(SnapshotError):
            load_node(blob, MockBackend())


def test_rng_identity_shared_between_net_and_protocols():
    """QHB stores the builder rng; the net schedules with the same object.
    After restore they must still be the SAME object, or replay diverges."""

    def make(ni, b, rng):
        return QueueingHoneyBadgerBuilder(ni, b, rng).batch_size(2).build()

    net = (
        NetBuilder(range(4)).backend(MockBackend()).using(make).build(seed=2)
    )
    assert net.nodes[0].algorithm.rng is net.rng
    net2 = load_node(save_node(net), MockBackend())
    assert net2.nodes[0].algorithm.rng is net2.rng


# ---------------------------------------------------------------------------
# Dynamic twin of the snapshot-coverage lint rule (PR 17)
# ---------------------------------------------------------------------------


def _iter_state_instances(root):
    """Walk the state graph exactly as the encoder would — registered
    instances via ``_state_attrs`` (env attrs dropped), containers
    element-wise — and yield every live ``_STATE_MODULES`` instance."""
    from hbbft_tpu.utils.snapshot import _registry, _state_attrs

    registered = set(_registry().values())
    seen, out, stack = set(), [], [root]
    while stack:
        obj = stack.pop()
        if id(obj) in seen:
            continue
        seen.add(id(obj))
        if isinstance(obj, (list, tuple, set, frozenset)):
            stack.extend(obj)
        elif isinstance(obj, dict):
            stack.extend(obj.keys())
            stack.extend(obj.values())
        elif type(obj) in registered:
            out.append(obj)
            stack.extend(v for _, v in _state_attrs(obj))
    return out


def test_dynamic_twin_live_state_instances_roundtrip_key_identical():
    """After a composed gauntlet smoke cell, every live ``_STATE_MODULES``
    instance snapshots and restores with an identical attribute-key set —
    the dynamic twin of the static ``snapshot-coverage`` rule, catching
    drift the AST pass cannot see (setattr through helpers, dynamically
    added attrs, hooks installed by the environment mid-run).

    The whole net round-trips once (one encode pass over every live
    instance: any undeclared callable dies here), then per class a small
    sample of instances is restored individually and its ``_state_attrs``
    key set diffed against the live object's.  Every declared env attr
    must resolve on the class, or restore would raise AttributeError."""
    from hbbft_tpu.net.scenarios import Cell, run_cell
    from hbbft_tpu.utils.snapshot import _state_attrs

    sink = []
    cell = Cell(
        attack="equivocate", schedule="partition_heal", churn="era_flip",
        crash="one_restart", traffic="one_x", n=4, epochs=6, seed=3,
    )
    run_cell(cell, net_sink=sink)
    (net,) = sink

    # one whole-graph encode/decode: the encoder rejects any callable
    # that coverage drift let into state, package-wide
    whole = load_node(save_node(net), net.backend)
    assert type(whole) is type(net)

    instances = _iter_state_instances(net)
    assert len(instances) > 50, "state graph unexpectedly small"
    by_class = {}
    for obj in instances:
        by_class.setdefault(type(obj), []).append(obj)
    assert any(c.__name__ == "VirtualNet" for c in by_class)
    assert any(c.__name__ == "CrashManager" for c in by_class)
    assert any(c.__name__ == "QueueingHoneyBadger" for c in by_class)

    for cls in sorted(by_class, key=lambda c: c.__qualname__):
        for env_name in getattr(cls, "_SNAPSHOT_ENV_ATTRS", ()):
            assert hasattr(cls, env_name), (
                f"{cls.__qualname__} declares env attr {env_name!r} with no "
                f"class-body default: restore would raise AttributeError"
            )
        for obj in by_class[cls][:3]:  # per-class sample: shapes are per-class
            restored = load_node(save_node(obj), net.backend)
            assert type(restored) is cls
            live_keys = {n for n, _ in _state_attrs(obj)}
            restored_keys = {n for n, _ in _state_attrs(restored)}
            assert restored_keys == live_keys, (
                cls.__qualname__,
                sorted(restored_keys ^ live_keys),
            )
