"""SyncKeyGen tests (reference: the worked doc-test in `src/sync_key_gen.rs` §
plus `tests/sync_key_gen.rs` §): a full dealer-less key generation whose
output keys actually sign/verify/combine, agreement on the public key set,
and fault handling for corrupted parts/acks."""

import random

import pytest

from hbbft_tpu.crypto.group import MockGroup
from hbbft_tpu.crypto.keys import SecretKey
from hbbft_tpu.protocols.sync_key_gen import Ack, Part, SyncKeyGen


def run_dkg(n=4, threshold=1, seed=0, group=None, drop_proposer=None):
    """Run a full synchronous DKG among n nodes; returns (pk_set, shares)."""
    g = group or MockGroup()
    rng = random.Random(seed)
    sks = {i: SecretKey.random(g, rng) for i in range(n)}
    pks = {i: sk.public_key() for i, sk in sks.items()}
    nodes = {}
    parts = {}
    for i in range(n):
        kg, part = SyncKeyGen.new(i, sks[i], pks, threshold, rng, g)
        nodes[i] = kg
        if part is not None and i != drop_proposer:
            parts[i] = part
    # Everyone handles every part, producing acks; everyone handles all acks.
    acks = []
    for proposer in sorted(parts):
        for i in range(n):
            out = nodes[i].handle_part(proposer, parts[proposer], rng)
            assert out.fault is None, out.fault
            if out.ack is not None:
                acks.append((i, out.ack))
    for acker, ack in acks:
        for i in range(n):
            out = nodes[i].handle_ack(acker, ack)
            assert out.fault is None, out.fault
    results = {i: nodes[i].generate() for i in range(n)}
    pk_sets = {i: r[0] for i, r in results.items()}
    shares = {i: r[1] for i, r in results.items()}
    # All nodes derive the same public key set.
    assert all(pk_sets[i] == pk_sets[0] for i in range(n))
    return pk_sets[0], shares


@pytest.mark.parametrize("n,t", [(4, 1), (7, 2), (4, 0)])
def test_generated_keys_work(n, t):
    pk_set, shares = run_dkg(n, t, seed=1)
    assert pk_set.threshold() == t
    doc = b"dkg doc"
    sig_shares = {i: shares[i].sign_share(doc) for i in range(t + 1)}
    for i in range(t + 1):
        assert pk_set.public_key_share(i).verify_sig_share(sig_shares[i], doc)
    sig = pk_set.combine_signatures(sig_shares)
    assert pk_set.public_key().verify(sig, doc)
    # Different subset combines to the same signature.
    sig2 = pk_set.combine_signatures(
        {i: shares[i].sign_share(doc) for i in range(n - t - 1, n)}
    )
    assert sig == sig2


def test_generated_keys_encrypt():
    pk_set, shares = run_dkg(4, 1, seed=2)
    rng = random.Random(9)
    msg = b"post-dkg secret"
    ct = pk_set.encrypt(msg, rng)
    dshares = {}
    for i in (1, 3):
        d = shares[i].decrypt_share(ct)
        assert pk_set.public_key_share(i).verify_decryption_share(d, ct)
        dshares[i] = d
    assert pk_set.combine_decryption_shares(dshares, ct) == msg


def test_dkg_tolerates_missing_proposer():
    """One proposer never sends a Part; the other N-1 parts suffice."""
    pk_set, shares = run_dkg(4, 1, seed=3, drop_proposer=2)
    doc = b"x"
    sig = pk_set.combine_signatures(
        {i: shares[i].sign_share(doc) for i in (0, 2)}
    )
    assert pk_set.public_key().verify(sig, doc)


def test_corrupt_part_rows_faulted():
    g = MockGroup()
    rng = random.Random(4)
    sks = {i: SecretKey.random(g, rng) for i in range(4)}
    pks = {i: sk.public_key() for i, sk in sks.items()}
    kg0, _ = SyncKeyGen.new(0, sks[0], pks, 1, rng, g)
    _, part1 = SyncKeyGen.new(1, sks[1], pks, 1, rng, g)
    # Corrupt node 0's encrypted row.
    rows = list(part1.rows)
    rows[0] = rows[0][:-1] + bytes([rows[0][-1] ^ 1])
    out = kg0.handle_part(1, Part(part1.commit, tuple(rows)), rng)
    assert out.fault in (
        "sync_key_gen:invalid_row_encryption",
        "sync_key_gen:row_commitment_mismatch",
    )


def test_wrong_ack_value_faulted():
    g = MockGroup()
    rng = random.Random(5)
    sks = {i: SecretKey.random(g, rng) for i in range(4)}
    pks = {i: sk.public_key() for i, sk in sks.items()}
    nodes = {}
    parts = {}
    for i in range(4):
        kg, part = SyncKeyGen.new(i, sks[i], pks, 1, rng, g)
        nodes[i] = kg
        parts[i] = part
    out0 = nodes[0].handle_part(1, parts[1], rng)
    out2 = nodes[2].handle_part(1, parts[1], rng)
    assert out0.ack and out2.ack
    # Node 2 lies in its ack to node 0: re-encrypt a wrong value for slot 0.
    from hbbft_tpu.utils import canonical

    vals = list(out2.ack.values)
    vals[0] = pks[0].encrypt(canonical.encode(12345), rng).to_bytes()
    bad_ack = Ack(out2.ack.proposer_idx, tuple(vals))
    assert nodes[0].handle_ack(2, bad_ack).fault == "sync_key_gen:ack_value_mismatch"
    # An honest ack still passes.
    assert nodes[0].handle_ack(0, out0.ack).fault is None


def test_ack_before_part_is_buffered():
    g = MockGroup()
    rng = random.Random(6)
    sks = {i: SecretKey.random(g, rng) for i in range(4)}
    pks = {i: sk.public_key() for i, sk in sks.items()}
    nodes = {}
    parts = {}
    for i in range(4):
        kg, part = SyncKeyGen.new(i, sks[i], pks, 1, rng, g)
        nodes[i] = kg
        parts[i] = part
    # Node 1 acks part 0; node 2 receives the ack *before* part 0.
    ack = nodes[1].handle_part(0, parts[0], rng).ack
    assert nodes[2].handle_ack(1, ack).fault is None  # buffered
    assert nodes[2].handle_part(0, parts[0], rng).fault is None
    assert 1 in nodes[2].parts[0].acks  # drained


def test_not_ready_raises():
    g = MockGroup()
    rng = random.Random(7)
    sks = {i: SecretKey.random(g, rng) for i in range(4)}
    pks = {i: sk.public_key() for i, sk in sks.items()}
    kg, _ = SyncKeyGen.new(0, sks[0], pks, 1, rng, g)
    with pytest.raises(ValueError):
        kg.generate()


@pytest.mark.slow
def test_dkg_on_real_curve():
    from hbbft_tpu.crypto.bls381 import BLS381Group

    pk_set, shares = run_dkg(4, 1, seed=8, group=BLS381Group())
    doc = b"real curve dkg"
    sig_shares = {i: shares[i].sign_share(doc) for i in (0, 3)}
    for i in (0, 3):
        assert pk_set.public_key_share(i).verify_sig_share(sig_shares[i], doc)
    sig = pk_set.combine_signatures(sig_shares)
    assert pk_set.public_key().verify(sig, doc)


def test_bivar_col_matches_full_evaluation_asymmetric():
    """col(y).evaluate(x) must equal evaluate(x, y) even for MALICIOUSLY
    ASYMMETRIC commitments (BivarCommitment.from_bytes accepts them
    unvalidated), and col must NOT equal row there — the ack cross-check's
    security depends on evaluating in the acker variable, not the
    receiver's (sync_key_gen._apply_ack)."""
    from hbbft_tpu.crypto.poly import BivarCommitment

    g = MockGroup()
    # asymmetric coefficient matrix: coeffs[i][j] != coeffs[j][i]
    coeffs = [[1, 2, 3], [40, 5, 6], [700, 80, 9]]
    c = BivarCommitment(g, coeffs)
    for x in (1, 2, 5):
        for y in (1, 3, 4):
            assert c.col(y).evaluate(x) == c.evaluate(x, y)
            assert c.row(x).evaluate(y) == c.evaluate(x, y)
    # and the two projections genuinely differ on asymmetric input
    assert c.col(2).coeffs != c.row(2).coeffs
