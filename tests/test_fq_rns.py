"""Goldens for the RNS/MXU Fq implementation (ops/fq_rns.py).

Direct-import tests cover the representation itself against Python-int
arithmetic; one subprocess test locks the HBBFT_TPU_FQ_IMPL=rns facade
end-to-end through the tower (the full curve/pairing suites are run
under the flag manually / in perf passes — they share the same seam).
"""

import os
import random
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq_rns as R


@pytest.fixture(scope="module")
def rng():
    return random.Random(42)


def _dev(x: int):
    return jnp.asarray(R.from_int(x))


def test_roundtrip_and_montgomery_form(rng):
    for _ in range(8):
        x = rng.randrange(Q)
        assert R.to_int(R.from_int(x)) == x
    assert R.to_int(np.asarray(R.ZERO)) == 0
    assert R.to_int(R.ONE) == 1


def test_mul_matches_python(rng):
    for _ in range(16):
        a, b = rng.randrange(Q), rng.randrange(Q)
        assert R.to_int(np.asarray(R.mul(_dev(a), _dev(b)))) == a * b % Q


def test_mul_batched_shapes(rng):
    xs = [rng.randrange(Q) for _ in range(6)]
    ys = [rng.randrange(Q) for _ in range(6)]
    A = jnp.asarray(R.from_ints(xs))
    B = jnp.asarray(R.from_ints(ys))
    got = R.to_ints(np.asarray(R.mul(A, B)))
    assert got == [x * y % Q for x, y in zip(xs, ys)]


def test_lazy_chain_with_negatives(rng):
    """Adds/subs drift residues out of range and the VALUE negative; mul
    renormalizes both (the sign-offset + S-K exactness claim)."""
    for _ in range(6):
        vals = [rng.randrange(Q) for _ in range(12)]
        acc = _dev(vals[0])
        accv = vals[0]
        for v in vals[1:6]:
            acc = R.add(acc, _dev(v))
            accv += v
        for v in vals[6:]:
            acc = R.sub(acc, _dev(v))
            accv -= v  # accv frequently negative here
        got = R.to_int(np.asarray(R.mul(acc, _dev(vals[0]))))
        assert got == accv * vals[0] % Q


def test_deep_linear_chain_via_reduce_small(rng):
    """The cyclo-sqr growth pattern: value doubles per step; reduce_small
    must renormalize so 64 chained steps stay exact."""
    x = rng.randrange(Q)
    acc = _dev(x)
    accv = x
    for _ in range(64):
        acc = R.reduce_small(R.add(acc, acc))
        accv = 2 * accv % Q
    assert R.to_int(np.asarray(acc)) == accv


def test_mul_small_range(rng):
    a = rng.randrange(Q)
    for k in (0, 1, 2, 3, 12, 64, -64, 65, -65, 4097, 32767, -32767):
        got = R.to_int(np.asarray(R.mul_small(_dev(a), k)))
        assert got == a * k % Q, k
    with pytest.raises(ValueError):
        R.mul_small(_dev(a), 1 << 15)


def test_pow_inv_batch_inv(rng):
    a = rng.randrange(1, Q)
    assert R.to_int(np.asarray(R.pow_fixed(_dev(a), 5))) == pow(a, 5, Q)
    assert R.to_int(np.asarray(R.inv(_dev(a)))) == pow(a, -1, Q)
    xs = [rng.randrange(1, Q) for _ in range(4)]
    got = R.to_ints(np.asarray(R.batch_inv(jnp.asarray(R.from_ints(xs)))))
    assert got == [pow(x, -1, Q) for x in xs]


def test_select_and_zero(rng):
    a, b = _dev(rng.randrange(Q)), _dev(rng.randrange(Q))
    cond = jnp.asarray(True)
    assert R.to_int(np.asarray(R.select(cond, a, b))) == R.to_int(np.asarray(a))
    assert R.is_zero_host(np.asarray(R.ZERO))
    assert not R.is_zero_host(np.asarray(R.ONE))


def test_batch_inv_lazy_endpoints(rng):
    """batch_inv over LAZY stacks: associative_scan passes endpoint
    elements through raw, so lanes grown by chained adds (legal under
    the value discipline) must be carried before the no-recarry scan —
    regression for the (−p, 2p) contract violation at the wings."""
    xs = [rng.randrange(1, Q) for _ in range(4)]
    base = R.from_ints(xs)
    lazy = base
    for _ in range(5):  # lanes up to ~32·p — far outside (−p, 2p)
        lazy = R.add(lazy, lazy)
    vals = [(x << 5) % Q for x in xs]
    got = R.to_ints(np.asarray(R.batch_inv(jnp.asarray(lazy))))
    assert got == [pow(v, -1, Q) for v in vals]


def test_exactness_margins():
    """Every f32 intermediate bound the module relies on, re-derived."""
    # extension partial sums: 39 terms of (p-1)*63 / (p-1)*31
    pmax = max(R.B1 + R.B2)
    assert R.N_B * (pmax - 1) * 63 < 1 << 24
    # pointwise products of reduced lanes
    assert (pmax - 1) ** 2 < 1 << 24
    # the TIGHTEST bound mul relies on: the fused r2r reduction's
    # x2r·M1⁻¹ + q̂·(Q·M1⁻¹) sum, x2r ∈ (−p, 3p) → < 4p² (~0.9% margin)
    assert 4 * pmax * pmax < 1 << 24
    # closure: M1 over the offset bound
    assert R.M1 > (Q << 34)
    assert R._X_OFFSET_INT % Q == 0
    # S-K digit fits the redundant modulus
    assert R.M_R > R.N_B + 2


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_ext_matmul_modes_golden(mode):
    """HBBFT_TPU_RNS_EXT plane-split strategies must be bit-identical to
    the HIGHEST default (env read at import → subprocess)."""
    import os
    import subprocess
    import sys

    code = """
import jax; jax.config.update("jax_platforms", "cpu")
import random
import numpy as np
from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq_rns as R
rng = random.Random(13)
xs = [rng.randrange(Q) for _ in range(6)]
ys = [rng.randrange(Q) for _ in range(6)]
a = np.asarray(R.from_ints(xs)); b = np.asarray(R.from_ints(ys))
got = R.to_ints(np.asarray(R.mul(a, b)))
assert got == [x * y % Q for x, y in zip(xs, ys)], got
inv = R.to_int(np.asarray(R.inv(np.asarray(R.from_int(xs[0])))))
assert inv == pow(xs[0], -1, Q)
print("OK", R._EXT_MODE)
"""
    env = dict(os.environ)
    env["HBBFT_TPU_RNS_EXT"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert f"OK {mode}" in proc.stdout


def test_facade_subprocess_tower_pairing():
    """HBBFT_TPU_FQ_IMPL=rns swaps the facade: the tower stack must stay
    golden end-to-end (one fq12 mul + a cyclo chain under the flag)."""
    code = """
import jax; jax.config.update("jax_platforms", "cpu")
import random
from hbbft_tpu.ops import fq, tower
from hbbft_tpu.crypto import bls381 as gold
assert fq.NLIMBS == 79, fq.NLIMBS  # facade engaged
rng = random.Random(3)
def rnd_fq12():
    return tuple(
        tuple(tuple(rng.randrange(gold.Q) for _ in range(2)) for _ in range(3))
        for _ in range(2)
    )
a, b = rnd_fq12(), rnd_fq12()
dev = tower.fq12_mul(tower.fq12_stack([a]), tower.fq12_stack([b]))
assert tower.fq12_to_ints(dev, 0) == gold.fq12_mul(a, b)
print("FACADE_OK")
"""
    env = dict(os.environ)
    env["HBBFT_TPU_FQ_IMPL"] = "rns"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert "FACADE_OK" in proc.stdout, proc.stdout + proc.stderr


# hypothesis is optional in the image: only this one property test needs
# it, and the deterministic tests above must keep collecting without it.
try:
    from hypothesis import HealthCheck, given, settings, strategies as st

    _HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - exercised only without hypothesis
    _HAVE_HYPOTHESIS = False

    def _no_hypothesis(*_a, **_k):
        def deco(fn):
            return pytest.mark.skip(reason="hypothesis not installed")(fn)

        return deco

    given = settings = _no_hypothesis

    class _St:
        def __getattr__(self, _name):
            return lambda *a, **k: None

    st = _St()

    class HealthCheck:
        too_slow = None


@given(
    ops=st.lists(
        st.tuples(
            st.sampled_from(["add", "sub", "mul", "neg", "small"]),
            st.integers(0, Q - 1),
            st.integers(-(1 << 15) + 1, (1 << 15) - 1),
        ),
        min_size=1,
        max_size=12,
    ),
    seed=st.integers(0, Q - 1),
)
@settings(
    max_examples=30,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
def test_random_op_sequences_match_python(ops, seed):
    """Arbitrary interleavings of lazy adds/subs/negs, Montgomery muls and
    small scalings agree with Python-int field arithmetic — the lazy
    value-domain closure argument (|v| < 2^16·Q) holds on every prefix
    because each mul/mul_small renormalizes and chains are ≤ 12 ops."""
    acc = jnp.asarray(R.from_int(seed))
    ref = seed
    for kind, operand, k in ops:
        if kind == "add":
            acc = R.add(acc, jnp.asarray(R.from_int(operand)))
            ref = ref + operand
        elif kind == "sub":
            acc = R.sub(acc, jnp.asarray(R.from_int(operand)))
            ref = ref - operand
        elif kind == "neg":
            acc = R.neg(acc)
            ref = -ref
        elif kind == "mul":
            acc = R.mul(acc, jnp.asarray(R.from_int(operand)))
            ref = ref * operand
        else:  # small
            acc = R.mul_small(acc, k)
            ref = ref * k
    assert R.to_int(np.asarray(acc)) == ref % Q
