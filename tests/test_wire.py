"""Wire serialization round-trips for the whole message hierarchy."""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.merkle import MerkleTree
from hbbft_tpu.protocols.binary_agreement import BaMessage
from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.broadcast import BroadcastMessage
from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage
from hbbft_tpu.protocols.honey_badger import HbMessage
from hbbft_tpu.protocols.sbv_broadcast import SbvMessage
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.protocols.subset import SubsetMessage
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage
from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage
from hbbft_tpu.utils.wire import WireError, decode_message, encode_message


@pytest.fixture(scope="module")
def group():
    return MockBackend().group


@pytest.fixture(scope="module")
def shares(group):
    rng = random.Random(4)
    sks = SecretKeySet.random(group, 1, rng)
    sig = sks.secret_key_share(0).sign_share(b"doc")
    ct = sks.public_keys().public_key().encrypt(b"msg0123456789abc", rng)
    dec = sks.secret_key_share(1).decrypt_share_unchecked(ct)
    return sig, dec


def _roundtrip(msg, group):
    data = encode_message(msg)
    assert isinstance(data, bytes)
    out = decode_message(data, group)
    assert encode_message(out) == data
    return out


def test_sbv_and_ba(group, shares):
    sig, _ = shares
    for msg in (
        SbvMessage.bval(True),
        SbvMessage.aux(False),
        BaMessage.sbv(0, SbvMessage.bval(False)),
        BaMessage.conf(2, BoolSet.both()),
        BaMessage.coin(5, ThresholdSignMessage(sig)),
        BaMessage.term(1, True),
    ):
        out = _roundtrip(msg, group)
        assert type(out) is type(msg)


def test_broadcast_proofs(group):
    tree = MerkleTree([bytes([i]) * 8 for i in range(6)])
    for msg in (
        BroadcastMessage.value(tree.proof(2)),
        BroadcastMessage.echo(tree.proof(5)),
        BroadcastMessage.ready(tree.root_hash),
    ):
        out = _roundtrip(msg, group)
        assert out == msg


def test_full_stack_envelopes(group, shares):
    sig, dec = shares
    inner = SubsetMessage(3, "broadcast", BroadcastMessage.ready(b"\x07" * 32))
    hb = HbMessage.subset(4, inner)
    dhb = DhbMessage(1, hb)
    sq = SqMessage.algo(dhb)
    out = _roundtrip(sq, group)
    assert out.payload.era == 1
    assert out.payload.payload.epoch == 4
    assert out.payload.payload.payload.proposer == 3

    hb2 = HbMessage.dec_share(9, 2, ThresholdDecryptMessage(dec))
    out = _roundtrip(DhbMessage(0, hb2), group)
    assert out.payload.kind == "dec_share"

    out = _roundtrip(SqMessage.epoch_started(2, 7), group)
    assert out.payload == (2, 7)


def test_malformed_rejected(group):
    from hbbft_tpu.utils import canonical

    bad = [
        b"\xff\x00garbage",
        canonical.encode(("sbv", "bval", 1)),  # non-bool value
        canonical.encode(("ba", -1, "term", True)),  # negative round
        canonical.encode(("ba", 0, "conf", 9)),  # bits out of range
        canonical.encode(("bc", "ready", b"short")),
        canonical.encode(("hb", 0, "subset", 1, ("sbv", "bval", True))),
        canonical.encode(("nope", 1)),
    ]
    for data in bad:
        with pytest.raises(WireError):
            decode_message(data, group)


def test_tampered_share_bytes_rejected(group, shares):
    sig, _ = shares
    data = encode_message(ThresholdSignMessage(sig))
    # flip a byte inside the share encoding
    broken = bytearray(data)
    broken[-1] ^= 0xFF
    try:
        out = decode_message(bytes(broken), group)
        # If it still parses, it must at least differ from the original.
        assert encode_message(out) != data
    except (WireError, Exception):
        pass
