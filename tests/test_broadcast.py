"""Broadcast integration tests (reference `tests/broadcast.rs` § shape):
with a correct proposer all correct nodes deliver the proposer's value; with
a faulty proposer they deliver identically or not at all."""

import pytest

from hbbft_tpu.net.adversary import NodeOrderAdversary, ReorderingAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.broadcast import Broadcast, BroadcastMessage

PAYLOAD = b"broadcast me " * 10


def build(n, f=0, adversary=None, seed=0, proposer=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .using(lambda ni, be: Broadcast(ni, proposer_id=proposer))
        .crank_limit(500_000)
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


@pytest.mark.parametrize("n,f", [(1, 0), (2, 0), (4, 1), (7, 2), (10, 3)])
def test_correct_proposer_delivers_everywhere(n, f):
    net = build(n, f)
    net.send_input(0, PAYLOAD)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [PAYLOAD], f"node {node.id}: {node.outputs}"


@pytest.mark.parametrize("size", [0, 1, 3, 100, 10_000])
def test_payload_sizes(size):
    payload = bytes(i % 256 for i in range(size))
    net = build(4, 1)
    net.send_input(0, payload)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [payload]


@pytest.mark.parametrize("adversary_cls", [ReorderingAdversary, NodeOrderAdversary])
@pytest.mark.parametrize("seed", range(3))
def test_adversarial_scheduling(adversary_cls, seed):
    net = build(7, 2, adversary=adversary_cls(), seed=seed)
    net.send_input(0, PAYLOAD)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [PAYLOAD]


@pytest.mark.parametrize("seed", range(3))
def test_silent_faulty_non_proposer(seed):
    # Proposer is correct (we only mark others faulty via seed search).
    while True:
        net = build(7, 2, adversary=SilentAdversary(), seed=seed)
        if not net.nodes[0].faulty:
            break
        seed += 100
    net.send_input(0, PAYLOAD)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [PAYLOAD]


def test_silent_proposer_delivers_nowhere():
    # A crashed proposer: nobody outputs, nobody crashes.
    seed = 0
    while True:
        net = build(4, 1, adversary=SilentAdversary(), seed=seed)
        if net.nodes[0].faulty:
            break
        seed += 1
    net.send_input(0, PAYLOAD)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == []


def test_equivocating_proposer_agreement():
    """A proposer that sends two different values: all correct nodes must
    agree (deliver the same value or none) — Bracha's guarantee."""
    from hbbft_tpu.core.types import Step, Target, TargetedMessage

    for seed in range(8):
        net = build(4, 0, seed=seed)
        proposer = net.nodes[0].algorithm
        # Manually construct two conflicting shard sets and interleave them.
        step_a = proposer.broadcast(b"value A" * 5)
        # Reset proposer state to let it produce a second, conflicting set.
        proposer.has_value = False
        proposer.echo_sent = False
        step_b = proposer.broadcast(b"value B" * 5)
        # Deliver A's messages to nodes 1,2 and B's to node 3 (mixed world).
        from hbbft_tpu.net.virtual_net import NetMessage

        for tm in step_a.messages:
            for to in tm.target.recipients(sorted(net.nodes), our_id=0):
                if to in (1, 2):
                    net.queue.append(NetMessage(0, to, tm.message))
        for tm in step_b.messages:
            for to in tm.target.recipients(sorted(net.nodes), our_id=0):
                if to == 3:
                    net.queue.append(NetMessage(0, to, tm.message))
        net.crank_to_quiescence()
        outs = [tuple(net.nodes[i].outputs) for i in (1, 2, 3)]
        delivered = {o for o in outs if o}
        assert len(delivered) <= 1, f"seed {seed}: equivocation let through: {outs}"
