"""MeshBackend: sharded crypto batches on the virtual 8-device CPU mesh."""

import random

import pytest

import jax

from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.parallel import MeshBackend, device_mesh


@pytest.fixture(scope="module")
def backend():
    assert len(jax.devices()) >= 8, "conftest must provide the virtual mesh"
    return MeshBackend(device_mesh(8))


@pytest.fixture(scope="module")
def keyset(backend):
    rng = random.Random(13)
    sks = backend.generate_key_set(1, rng)
    return sks, sks.public_keys()


@pytest.fixture()
def rng():
    return random.Random(99)


def test_bucket_widens_to_mesh(backend):
    # sub-threshold batches clamp to the single-device bucket (PR 18
    # satellite): a singleton no longer pads to 8 lanes of 7/8 waste —
    # it stays at the 4-lane minimum bucket and routes to one device
    assert backend._pad_bucket(1) == 4
    assert backend._pad_bucket(4) == 4
    # at/above the mesh width the bucket still widens to a mesh multiple
    assert backend._pad_bucket(8) == 8
    assert backend._pad_bucket(9) == 16
    assert backend._pad_bucket(64) % 8 == 0
    assert backend.name == "MeshBackend[8]"


def test_sharded_sig_share_verify(backend, keyset):
    sks, pks = keyset
    doc = b"mesh doc"
    items = []
    for i in range(3):
        share = sks.secret_key_share(i).sign_share(doc)
        items.append((pks.public_key_share(i), doc, share))
    # one forged share
    bad = sks.secret_key_share(0).sign_share(b"other doc")
    items.append((pks.public_key_share(1), doc, bad))
    assert backend.verify_sig_shares(items) == [True, True, True, False]


def test_sharded_decrypt_roundtrip(backend, keyset, rng):
    sks, pks = keyset
    msg = b"sharded threshold decryption"
    ct = pks.encrypt(msg, rng)
    assert backend.verify_ciphertexts([ct]) == [True]
    shares = {
        i: sks.secret_key_share(i).decrypt_share_unchecked(ct) for i in (0, 2)
    }
    items = [(pks.public_key_share(i), ct, s) for i, s in shares.items()]
    assert backend.verify_dec_shares(items) == [True, True]
    backend.device_combine_threshold = 2  # force the sharded device combine
    try:
        out = backend.combine_dec_shares_batch(pks, [(shares, ct)] * 3)
    finally:
        backend.device_combine_threshold = 8
    assert out == [msg] * 3


def test_lane_capped_chunks_across_mesh(backend, keyset, rng):
    """Chunking × sharding (round-2 verdict Weak #7): a combine batch
    above device_lane_cap must split into lane-capped chunks, each chunk
    itself sharded across the 8-device mesh (bucket widened to a mesh
    multiple), with results correct and in order — the soak-shape (N=256
    scale item count) interaction the seam previously never exercised."""
    sks, pks = keyset
    n_items = 256  # soak-scale combine count (N=256 network, dedup'd)
    cts = []
    msgs = []
    items = []
    for j in range(n_items):
        msg = bytes([j % 251]) * 8
        ct = pks.encrypt(msg, rng)
        shares = {
            i: sks.secret_key_share(i).decrypt_share_unchecked(ct)
            for i in (0, 2)
        }
        items.append((shares, ct))
        cts.append(ct)
        msgs.append(msg)
    d0 = backend.counters.device_dispatches
    backend.device_combine_threshold = 2
    saved_cap = backend.device_lane_cap
    backend.device_lane_cap = 128  # k=2 → 64 items/chunk → 4 chunks
    try:
        got = backend.combine_dec_shares_batch(pks, items)
    finally:
        backend.device_combine_threshold = 8
        backend.device_lane_cap = saved_cap
    assert got == msgs
    assert backend.counters.device_dispatches == d0 + 4
    # each chunk's 64-item bucket is a mesh multiple, so it sharded evenly
    assert backend._pad_bucket(64) % 8 == 0


# ---------------------------------------------------------------------------
# Per-device pipelined shard dispatch (PR 18)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", (63, 64, 65))
def test_shard_killswitch_ab_at_chunk_boundaries(backend, keyset, monkeypatch, n):
    """PR 18 acceptance A/B: the sharded per-device run and the
    ``HBBFT_TPU_NO_SHARD_PIPE=1`` single-queue SPMD run produce
    bit-identical shares with conserved device_dispatches — at
    n == cap·n_dev − 1 (8 chunks, short tail), cap·n_dev (exactly the
    mesh), and cap·n_dev + 1 (sub-threshold tail host-folded)."""
    sks, _ = keyset
    doc = b"shard ab doc"
    items = [(sks.secret_key_share(i % 3), doc) for i in range(n)]
    saved = backend.device_lane_cap, backend.device_combine_threshold
    backend.device_lane_cap = 8  # cap·n_dev = 64
    backend.device_combine_threshold = 2
    try:
        monkeypatch.delenv("HBBFT_TPU_NO_SHARD_PIPE", raising=False)
        p0 = len(backend._pipe.placements)
        d0 = backend.counters.device_dispatches
        sharded = backend.sign_shares_batch(items)
        placements = backend._pipe.placements[p0:]
        disp_sharded = backend.counters.device_dispatches - d0
        monkeypatch.setenv("HBBFT_TPU_NO_SHARD_PIPE", "1")
        p1 = len(backend._pipe.placements)
        d1 = backend.counters.device_dispatches
        single = backend.sign_shares_batch(items)
        disp_single = backend.counters.device_dispatches - d1
    finally:
        backend.device_lane_cap, backend.device_combine_threshold = saved
    assert single == sharded  # bit-identical shares
    assert disp_single == disp_sharded == 8  # conserved dispatch count
    assert len(backend._pipe.placements) == p1  # killswitch: no reservations
    # whole chunks landed round-robin on 8 consecutive distinct devices
    assert len(placements) == 8
    assert placements == [(placements[0] + i) % 8 for i in range(8)]


def test_small_batch_clamps_to_single_device(backend, keyset, monkeypatch):
    """Satellite pin: a 3-item ladder pads to the 4-lane minimum bucket
    (1 pad lane) instead of the old lcm(bucket, n_dev) = 8 (5 pad
    lanes), riding ONE device whole — in both A/B arms (the SPMD arm
    routes the non-mesh-divisible chunk to a single device too)."""
    sks, _ = keyset
    doc = b"small batch"
    items = [(sks.secret_key_share(i), doc) for i in range(3)]
    golden = [sk.sign_share(d) for sk, d in items]
    saved = backend.device_combine_threshold
    backend.device_combine_threshold = 2
    try:
        monkeypatch.delenv("HBBFT_TPU_NO_SHARD_PIPE", raising=False)
        p0 = len(backend._pipe.placements)
        d0 = backend.counters.device_dispatches
        assert backend.sign_shares_batch(items) == golden
        assert backend.counters.device_dispatches == d0 + 1
        assert len(backend._pipe.placements) == p0 + 1  # one whole chunk
        monkeypatch.setenv("HBBFT_TPU_NO_SHARD_PIPE", "1")
        assert backend.sign_shares_batch(items) == golden
    finally:
        backend.device_combine_threshold = saved
    # pad-lane accounting: 4-lane bucket = 1 pad lane for 3 items
    assert backend._pad_bucket(3) == 4


def test_per_device_spans_sum_to_device_seconds(backend, keyset, tmp_path):
    """PR 18 observability acceptance: every sharded dispatch spans its
    device's ``device/<n>`` track, and the per-device span partition
    sums to counters.device_seconds within ±5% (tools/trace_report.py
    check_per_device_seconds + the report CLI)."""
    from hbbft_tpu.obs import Tracer
    from tools.trace_report import (
        check_per_device_seconds,
        load_events,
        main as tr_main,
        validate_chrome_trace,
    )

    sks, _ = keyset
    items = [(sks.secret_key_share(i % 3), b"per-device") for i in range(32)]
    backend.tracer = Tracer()
    saved = backend.device_lane_cap, backend.device_combine_threshold
    backend.device_lane_cap = 8  # 4 chunks on 4 distinct devices
    backend.device_combine_threshold = 2
    d0 = backend.counters.device_seconds
    try:
        assert len(backend.sign_shares_batch(items)) == 32
    finally:
        backend.device_lane_cap, backend.device_combine_threshold = saved
        tr = backend.tracer
        backend.tracer = None
    dev = backend.counters.device_seconds - d0
    path = str(tmp_path / "shard_trace.json")
    tr.write(path)
    events = load_events(path)
    assert validate_chrome_trace(events) == []
    ok, per = check_per_device_seconds(events, dev)
    assert ok, (per, dev)
    assert len([t for t in per if t.startswith("device/")]) >= 4
    assert tr_main([path, "--device-seconds", str(dev)]) == 0
