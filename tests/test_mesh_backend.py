"""MeshBackend: sharded crypto batches on the virtual 8-device CPU mesh."""

import random

import pytest

import jax

from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.parallel import MeshBackend, device_mesh


@pytest.fixture(scope="module")
def backend():
    assert len(jax.devices()) >= 8, "conftest must provide the virtual mesh"
    return MeshBackend(device_mesh(8))


@pytest.fixture(scope="module")
def keyset(backend):
    rng = random.Random(13)
    sks = backend.generate_key_set(1, rng)
    return sks, sks.public_keys()


@pytest.fixture()
def rng():
    return random.Random(99)


def test_bucket_widens_to_mesh(backend):
    assert backend._pad_bucket(1) % 8 == 0
    assert backend._pad_bucket(9) == 16
    assert backend.name == "MeshBackend[8]"


def test_sharded_sig_share_verify(backend, keyset):
    sks, pks = keyset
    doc = b"mesh doc"
    items = []
    for i in range(3):
        share = sks.secret_key_share(i).sign_share(doc)
        items.append((pks.public_key_share(i), doc, share))
    # one forged share
    bad = sks.secret_key_share(0).sign_share(b"other doc")
    items.append((pks.public_key_share(1), doc, bad))
    assert backend.verify_sig_shares(items) == [True, True, True, False]


def test_sharded_decrypt_roundtrip(backend, keyset, rng):
    sks, pks = keyset
    msg = b"sharded threshold decryption"
    ct = pks.encrypt(msg, rng)
    assert backend.verify_ciphertexts([ct]) == [True]
    shares = {
        i: sks.secret_key_share(i).decrypt_share_unchecked(ct) for i in (0, 2)
    }
    items = [(pks.public_key_share(i), ct, s) for i, s in shares.items()]
    assert backend.verify_dec_shares(items) == [True, True]
    backend.device_combine_threshold = 2  # force the sharded device combine
    try:
        out = backend.combine_dec_shares_batch(pks, [(shares, ct)] * 3)
    finally:
        backend.device_combine_threshold = 8
    assert out == [msg] * 3


def test_lane_capped_chunks_across_mesh(backend, keyset, rng):
    """Chunking × sharding (round-2 verdict Weak #7): a combine batch
    above device_lane_cap must split into lane-capped chunks, each chunk
    itself sharded across the 8-device mesh (bucket widened to a mesh
    multiple), with results correct and in order — the soak-shape (N=256
    scale item count) interaction the seam previously never exercised."""
    sks, pks = keyset
    n_items = 256  # soak-scale combine count (N=256 network, dedup'd)
    cts = []
    msgs = []
    items = []
    for j in range(n_items):
        msg = bytes([j % 251]) * 8
        ct = pks.encrypt(msg, rng)
        shares = {
            i: sks.secret_key_share(i).decrypt_share_unchecked(ct)
            for i in (0, 2)
        }
        items.append((shares, ct))
        cts.append(ct)
        msgs.append(msg)
    d0 = backend.counters.device_dispatches
    backend.device_combine_threshold = 2
    saved_cap = backend.device_lane_cap
    backend.device_lane_cap = 128  # k=2 → 64 items/chunk → 4 chunks
    try:
        got = backend.combine_dec_shares_batch(pks, items)
    finally:
        backend.device_combine_threshold = 8
        backend.device_lane_cap = saved_cap
    assert got == msgs
    assert backend.counters.device_dispatches == d0 + 4
    # each chunk's 64-item bucket is a mesh multiple, so it sharded evenly
    assert backend._pad_bucket(64) % 8 == 0
