"""Interpret-mode goldens for the fused RNS Montgomery kernel
(ops/fq_rns_pallas) against the XLA path (ops/fq_rns) and host ints.

The kernel is numerically EXACT by construction (every bound derived in
the module docstrings), so equality here is bit-for-bit on the
represented values — any drift is a real bug, not tolerance noise.
"""

import random

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq_rns as R
from hbbft_tpu.ops import fq_rns_pallas as K


@pytest.fixture
def rng():
    return random.Random(20260801)


def _lazy_stack(rng, n):
    """Residue stacks exercising the LAZY domain, not just canonical
    values: raw from_ints rows plus sums/differences/negations (lanes
    drift above p and below 0 — exactly what mul must renormalize)."""
    xs = [rng.randrange(Q) for _ in range(n)]
    base = R.from_ints(xs)
    lazy = np.concatenate(
        [base, base[: n // 2] + base[n // 2 : 2 * (n // 2)], -base[:1]]
    )
    vals = xs + [
        (xs[i] + xs[n // 2 + i]) % Q for i in range(n // 2)
    ] + [(-xs[0]) % Q]
    return jnp.asarray(lazy), vals


def test_mul_golden_vs_xla_and_host(rng):
    a, va = _lazy_stack(rng, 6)
    b, vb = _lazy_stack(rng, 6)
    got = np.asarray(K.mul(a, b, interpret=True))
    want = np.asarray(R.mul(a, b))
    assert R.to_ints(got) == R.to_ints(want)
    # and against host integers (strip the shared Montgomery factor)
    assert R.to_ints(got) == [x * y % Q for x, y in zip(va, vb)]


def test_mul_broadcast_and_padding(rng):
    # one lane vs a stack (broadcast), lane count far from a TILE multiple
    n = 7
    a, va = _lazy_stack(rng, n)
    x = rng.randrange(Q)
    b = jnp.asarray(R.from_int(x))
    got = R.to_ints(np.asarray(K.mul(a, b, interpret=True)))
    assert got == [v * x % Q for v in va]


def test_mul_multi_tile(rng):
    # lanes > TILE exercises the grid (2 tiles) without an interpret blowup
    n = K.TILE + 3
    xs = [rng.randrange(Q) for _ in range(8)]
    a = jnp.asarray(np.tile(R.from_ints(xs[:4]), (n // 4 + 1, 1))[:n])
    b = jnp.asarray(np.tile(R.from_ints(xs[4:]), (n // 4 + 1, 1))[:n])
    got = R.to_ints(np.asarray(K.mul(a, b, interpret=True)))
    want = [
        xs[i % 4] * xs[4 + i % 4] % Q for i in range(n)
    ]
    assert got == want


def test_mul_chain_golden(rng):
    a, va = _lazy_stack(rng, 4)
    b, vb = _lazy_stack(rng, 4)
    steps = 5
    got = R.to_ints(np.asarray(K.mul_chain(a, b, steps, interpret=True)))
    # in represented values the Montgomery form cancels: x·b^steps
    assert got == [x * pow(y, steps, Q) % Q for x, y in zip(va, vb)]


def test_pow_golden(rng):
    a, va = _lazy_stack(rng, 4)
    e = 0b1011010111  # 10 bits: both branches of the blend, multi-iteration
    got = R.to_ints(np.asarray(K.pow_fixed(a, e, interpret=True)))
    assert got == [pow(v, e, Q) for v in va]
    # parity with the XLA scan path
    assert got == R.to_ints(np.asarray(R.pow_fixed(a, e)))


def test_pow_exponent_one(rng):
    a, va = _lazy_stack(rng, 3)
    got = R.to_ints(np.asarray(K.pow_fixed(a, 1, interpret=True)))
    assert got == va


def test_facade_env_routing(rng, monkeypatch):
    """The HBBFT_TPU_RNS_FUSED decision table — positive rows under a
    mocked TPU backend (this suite runs on CPU), negative rows both ways
    (on a real CPU backend the dispatch must NEVER route, or interpret
    kernels would land in production graphs)."""
    table = [
        ("pow", "pow", True),
        ("pow", "mul", False),
        ("all", "mul", True),
        ("all", "pow", True),
        ("0", "pow", False),
        ("0", "mul", False),
    ]
    # real CPU backend: never route, whatever the mode says
    for mode, which, _ in table:
        monkeypatch.setenv("HBBFT_TPU_RNS_FUSED", mode)
        assert R._use_fused(which) is False
    # mocked TPU backend: the table is the contract
    monkeypatch.setattr(R.jax, "default_backend", lambda: "tpu")
    for mode, which, want in table:
        monkeypatch.setenv("HBBFT_TPU_RNS_FUSED", mode)
        assert R._use_fused(which) is want, (mode, which)
    monkeypatch.delenv("HBBFT_TPU_RNS_FUSED")
    assert R._use_fused("pow") is True  # default mode is pow
    assert R._use_fused("mul") is False
    monkeypatch.setenv("HBBFT_TPU_NO_PALLAS", "1")
    assert R._use_fused("pow") is False  # the bench fallback-ladder kill switch
