"""SenderQueue tests: epoch announcements gate delivery; consensus still
works wrapped; premature messages are buffered, obsolete ones dropped."""

import pytest

from hbbft_tpu.net.adversary import ReorderingAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage, DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import HbMessage
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue, SqMessage


def build(n, f=0, adversary=None, seed=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .crank_limit(10_000_000)
        .using(
            lambda ni, be, rng: SenderQueue(
                QueueingHoneyBadger(
                    ni, be, rng=rng, batch_size=3, session_id=b"test-sq"
                )
            )
        )
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


def committed_txs(node):
    out = []
    for batch in node.outputs:
        for p, txs in sorted(batch.contributions.items(), key=lambda kv: repr(kv[0])):
            if isinstance(txs, list):
                out.extend(tx for tx in txs if tx not in out)
    return out


@pytest.mark.parametrize("seed", range(3))
def test_wrapped_qhb_commits(seed):
    net = build(4, f=1, seed=seed)
    txs = [("tx", i) for i in range(6)]
    for tx in txs:
        for i in sorted(net.nodes):
            net._process_step(net.nodes[i], net.nodes[i].algorithm.push_transaction(tx))
    net.crank_until(
        lambda n: all(
            set(txs) <= set(committed_txs(node)) for node in n.correct_nodes()
        ),
        max_cranks=2_000_000,
    )
    orders = [committed_txs(node) for node in net.correct_nodes()]
    assert all(o == orders[0] for o in orders)


def test_premature_messages_buffered_until_announcement():
    net = build(4, seed=1)
    sq = net.nodes[0].algorithm
    # Peer 1 announces it is still at (era 0, epoch 0).
    net._process_step(net.nodes[0], sq.handle_message(1, SqMessage.epoch_started(0, 0)))
    # A far-future message for peer 1 must be buffered, not sent.
    from hbbft_tpu.core.types import Step, Target, TargetedMessage

    fake = DhbMessage(0, HbMessage.subset(10, "payload"))
    step = sq._route(TargetedMessage(Target.node(1), fake))
    assert step.messages == []
    assert fake in sq._outgoing[1]
    # Once the peer reaches epoch 8 (10 <= 8+3), the buffer flushes.
    flush = sq._on_epoch_started(1, (0, 8))
    sent = [tm for tm in flush.messages if tm.message.kind == "algo"]
    assert len(sent) == 1 and sent[0].message.payload is fake


def test_obsolete_messages_dropped():
    net = build(4, seed=2)
    sq = net.nodes[0].algorithm
    net._process_step(net.nodes[0], sq.handle_message(1, SqMessage.epoch_started(2, 5)))
    from hbbft_tpu.core.types import Target, TargetedMessage

    stale = DhbMessage(0, HbMessage.subset(0, "old"))
    step = sq._route(TargetedMessage(Target.node(1), stale))
    assert step.messages == []  # dropped silently
    assert not sq._outgoing.get(1)


def test_announcements_are_emitted():
    net = build(4, seed=3)
    for i in sorted(net.nodes):
        net._process_step(
            net.nodes[i], net.nodes[i].algorithm.push_transaction(("t", i))
        )
    net.crank_until(
        lambda n: all(len(node.outputs) >= 1 for node in n.correct_nodes()),
        max_cranks=1_000_000,
    )
    # After the first batch, peers know each other's progress.
    sq = net.nodes[0].algorithm
    assert sq.peer_epochs, "no epoch announcements received"
