"""Golden tests: JAX tower fields vs the pure-Python bls381 reference."""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import tower


@pytest.fixture(scope="module")
def rng():
    return random.Random(99)


def rnd_fq2(rng):
    return (rng.randrange(Q), rng.randrange(Q))


def rnd_fq6(rng):
    return tuple(rnd_fq2(rng) for _ in range(3))


def rnd_fq12(rng):
    return tuple(rnd_fq6(rng) for _ in range(2))


def test_fq2_ops(rng):
    xs = [rnd_fq2(rng) for _ in range(16)]
    ys = [rnd_fq2(rng) for _ in range(16)]
    a = tower.fq2_stack(xs)
    b = tower.fq2_stack(ys)

    got = tower.fq2_mul(a, b)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_mul(xs[i], ys[i])

    got = tower.fq2_sqr(a)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_sqr(xs[i])

    got = tower.fq2_mul_xi(a)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_mul_xi(xs[i])

    got = tower.fq2_inv(a)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_inv(xs[i])


def test_fq6_ops(rng):
    xs = [rnd_fq6(rng) for _ in range(8)]
    ys = [rnd_fq6(rng) for _ in range(8)]
    a = tower.fq6_stack(xs)
    b = tower.fq6_stack(ys)

    got = tower.fq6_mul(a, b)
    for i in range(8):
        assert tower.fq6_to_ints(got, i) == gold.fq6_mul(xs[i], ys[i])

    got = tower.fq6_mul_by_v(a)
    for i in range(8):
        assert tower.fq6_to_ints(got, i) == gold.fq6_mul_by_v(xs[i])

    got = tower.fq6_inv(a)
    for i in range(8):
        assert tower.fq6_to_ints(got, i) == gold.fq6_inv(xs[i])


def test_fq12_ops(rng):
    xs = [rnd_fq12(rng) for _ in range(4)]
    ys = [rnd_fq12(rng) for _ in range(4)]
    a = tower.fq12_stack(xs)
    b = tower.fq12_stack(ys)

    got = tower.fq12_mul(a, b)
    for i in range(4):
        assert tower.fq12_to_ints(got, i) == gold.fq12_mul(xs[i], ys[i])

    got = tower.fq12_sqr(a)
    for i in range(4):
        assert tower.fq12_to_ints(got, i) == gold.fq12_sqr(xs[i])

    got = tower.fq12_inv(a)
    for i in range(4):
        assert tower.fq12_to_ints(got, i) == gold.fq12_inv(xs[i])


def test_fq12_pow_fixed(rng):
    xs = [rnd_fq12(rng) for _ in range(2)]
    a = tower.fq12_stack(xs)
    e = 0xDEADBEEF12345
    got = tower.fq12_pow_fixed(a, e)
    for i in range(2):
        assert tower.fq12_to_ints(got, i) == gold.fq12_pow(xs[i], e)


def test_fq12_frobenius(rng):
    xs = [rnd_fq12(rng) for _ in range(2)]
    a = tower.fq12_stack(xs)
    got = tower.fq12_frobenius(a)
    for i in range(2):
        want = gold.fq12_pow(xs[i], Q)
        assert tower.fq12_to_ints(got, i) == want


def test_batch_inv_fq2(rng):
    xs = [rnd_fq2(rng) for _ in range(9)]
    a = tower.fq2_stack(xs)
    got = tower.batch_inv_fq2(a)
    for i in range(9):
        assert tower.fq2_to_ints(got, i) == gold.fq2_inv(xs[i])


def _rand_cyclotomic(rng):
    """Random element of the Φ₁₂ cyclotomic subgroup via the easy part."""
    f = rnd_fq12(rng)
    t = gold.fq12_mul(gold.fq12_conj(f), gold.fq12_inv(f))
    return gold.fq12_mul(gold.fq12_pow(t, Q * Q), t)


def test_fq12_cyclo_sqr(rng):
    cycs = [_rand_cyclotomic(rng) for _ in range(3)]
    dev = tower.fq12_stack(cycs)
    out = tower.fq12_cyclo_sqr(dev)
    for i, c in enumerate(cycs):
        assert tower.fq12_to_ints(out, i) == gold.fq12_sqr(c)


def test_fq12_cyclo_sqr_chained(rng):
    """64 chained squarings (the x-chain depth) stay exact — guards the
    limb renormalization against envelope overflow."""
    c = _rand_cyclotomic(rng)
    cur = tower.fq12_stack([c])
    for _ in range(64):
        cur = tower.fq12_cyclo_sqr(cur)
    assert tower.fq12_to_ints(cur, 0) == gold.fq12_pow(c, 1 << 64)


def test_fq12_cyclo_pow_segmented(rng):
    from hbbft_tpu.crypto.bls381 import BLS_X

    cycs = [_rand_cyclotomic(rng) for _ in range(2)]
    dev = tower.fq12_stack(cycs)
    for e in (BLS_X, 5, 1, 0b1000001):
        out = tower.fq12_cyclo_pow_segmented(dev, e)
        for i, c in enumerate(cycs):
            assert tower.fq12_to_ints(out, i) == gold.fq12_pow(c, e)


def test_fq12_mul_line(rng):
    zero2 = (0, 0)
    fs = [rnd_fq12(rng) for _ in range(3)]
    lines = [[rnd_fq2(rng) for _ in range(3)] for _ in range(3)]
    fdev = tower.fq12_stack(fs)
    ldev = tuple(tower.fq2_stack([l[k] for l in lines]) for k in range(3))
    out = tower.fq12_mul_line(fdev, ldev)
    for i in range(3):
        l0, l4, l5 = lines[i]
        sparse = ((l0, zero2, zero2), (zero2, l4, l5))
        assert tower.fq12_to_ints(out, i) == gold.fq12_mul(fs[i], sparse)
