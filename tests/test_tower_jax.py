"""Golden tests: JAX tower fields vs the pure-Python bls381 reference."""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import tower


@pytest.fixture(scope="module")
def rng():
    return random.Random(99)


def rnd_fq2(rng):
    return (rng.randrange(Q), rng.randrange(Q))


def rnd_fq6(rng):
    return tuple(rnd_fq2(rng) for _ in range(3))


def rnd_fq12(rng):
    return tuple(rnd_fq6(rng) for _ in range(2))


def test_fq2_ops(rng):
    xs = [rnd_fq2(rng) for _ in range(16)]
    ys = [rnd_fq2(rng) for _ in range(16)]
    a = tower.fq2_stack(xs)
    b = tower.fq2_stack(ys)

    got = tower.fq2_mul(a, b)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_mul(xs[i], ys[i])

    got = tower.fq2_sqr(a)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_sqr(xs[i])

    got = tower.fq2_mul_xi(a)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_mul_xi(xs[i])

    got = tower.fq2_inv(a)
    for i in range(16):
        assert tower.fq2_to_ints(got, i) == gold.fq2_inv(xs[i])


def test_fq6_ops(rng):
    xs = [rnd_fq6(rng) for _ in range(8)]
    ys = [rnd_fq6(rng) for _ in range(8)]
    a = tower.fq6_stack(xs)
    b = tower.fq6_stack(ys)

    got = tower.fq6_mul(a, b)
    for i in range(8):
        assert tower.fq6_to_ints(got, i) == gold.fq6_mul(xs[i], ys[i])

    got = tower.fq6_mul_by_v(a)
    for i in range(8):
        assert tower.fq6_to_ints(got, i) == gold.fq6_mul_by_v(xs[i])

    got = tower.fq6_inv(a)
    for i in range(8):
        assert tower.fq6_to_ints(got, i) == gold.fq6_inv(xs[i])


def test_fq12_ops(rng):
    xs = [rnd_fq12(rng) for _ in range(4)]
    ys = [rnd_fq12(rng) for _ in range(4)]
    a = tower.fq12_stack(xs)
    b = tower.fq12_stack(ys)

    got = tower.fq12_mul(a, b)
    for i in range(4):
        assert tower.fq12_to_ints(got, i) == gold.fq12_mul(xs[i], ys[i])

    got = tower.fq12_sqr(a)
    for i in range(4):
        assert tower.fq12_to_ints(got, i) == gold.fq12_sqr(xs[i])

    got = tower.fq12_inv(a)
    for i in range(4):
        assert tower.fq12_to_ints(got, i) == gold.fq12_inv(xs[i])


def test_fq12_pow_fixed(rng):
    xs = [rnd_fq12(rng) for _ in range(2)]
    a = tower.fq12_stack(xs)
    e = 0xDEADBEEF12345
    got = tower.fq12_pow_fixed(a, e)
    for i in range(2):
        assert tower.fq12_to_ints(got, i) == gold.fq12_pow(xs[i], e)


def test_fq12_frobenius(rng):
    xs = [rnd_fq12(rng) for _ in range(2)]
    a = tower.fq12_stack(xs)
    got = tower.fq12_frobenius(a)
    for i in range(2):
        want = gold.fq12_pow(xs[i], Q)
        assert tower.fq12_to_ints(got, i) == want


def test_batch_inv_fq2(rng):
    xs = [rnd_fq2(rng) for _ in range(9)]
    a = tower.fq2_stack(xs)
    got = tower.batch_inv_fq2(a)
    for i in range(9):
        assert tower.fq2_to_ints(got, i) == gold.fq2_inv(xs[i])
