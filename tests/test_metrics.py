"""Observability layer: counters + opt-in per-crank event log (SURVEY.md §5)."""

from hbbft_tpu.net.adversary import NullAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign
from hbbft_tpu.utils.metrics import Counters, EventLog


def _run_net(event_log=None):
    b = (
        NetBuilder(range(4))
        .num_faulty(1)
        .adversary(NullAdversary())
        .using(lambda ni, be: ThresholdSign(ni, be, doc=b"metrics"))
    )
    if event_log is not None:
        b = b.trace(event_log)
    net = b.build(seed=5)
    for nid in sorted(net.nodes):
        net.send_input(nid, None)
    net.crank_to_quiescence()
    return net


def test_counters_flow_through_threshold_sign():
    net = _run_net()
    m = net.metrics()
    assert m["messages_delivered"] > 0
    assert m["cranks"] == m["messages_delivered"]
    # Eager mode: each node verifies exactly one foreign share (its own
    # share needs no check; threshold+1 = 2 verified shares terminate it,
    # and later shares are ignored after termination).
    assert m["sig_shares_verified"] == 4
    assert m["pairing_checks"] >= 4
    # Each node combines threshold+1 = 2 shares once.
    assert m["sig_shares_combined"] == 8
    assert m["faults_recorded"] == 0


def test_event_log_records_cranks_and_is_optional():
    log = EventLog()
    net = _run_net(event_log=log)
    cranks = log.of_type("crank")
    assert len(cranks) == net.messages_delivered
    ev = cranks[0]
    assert {"crank", "sender", "to", "msg_type", "outputs"} <= set(ev)
    assert ev["msg_type"] == "ThresholdSignMessage"
    # No log attached: runtime must not create one implicitly.
    net2 = _run_net()
    assert net2.event_log is None


def test_event_log_capacity_bound():
    log = EventLog(capacity=100)
    for i in range(250):
        log.emit(event="x", i=i)
    # ring buffer: exactly the newest `capacity` events survive, and the
    # dropped count is exact (the old list store evicted in 10% batches)
    assert len(log) == 100
    assert log.dropped == 150
    assert list(log.events)[0]["i"] == 150  # oldest survivor
    assert list(log.events)[-1]["i"] == 249


def test_counters_delta_measurement_window():
    # delta() is the windowed-measurement replacement for a mid-run
    # reset(): every field's change since a snapshot, zeros INCLUDED,
    # while the live counters stay monotonic (a reset on a shared
    # backend would skew every run-end aggregate read after it)
    c = Counters()
    c.pairing_checks = 7
    c.device_seconds = 1.25
    since = c.snapshot()
    c.pairing_checks += 3
    d = c.delta(since)
    assert d["pairing_checks"] == 3
    assert d["device_seconds"] == 0.0
    assert set(d) == set(Counters().snapshot())
    assert c.pairing_checks == 10 and c.device_seconds == 1.25


def test_counters_diff_and_merge():
    c = Counters()
    snap = c.snapshot()
    c.pairing_checks += 5
    assert c.diff(snap) == {"pairing_checks": 5}
    d = Counters()
    d.cranks = 2
    merged = c.merged_with(d)
    assert merged["pairing_checks"] == 5 and merged["cranks"] == 2
