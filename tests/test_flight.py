"""Failure flight recorder (obs/flight.py): the evidence ring, bundle
structure and validation, the auto-dump on failing runs (naming the
injected fault), checkpoint detachment, and seeded-replay identity of
the dumped bundles."""

import json

import pytest

from hbbft_tpu.net.scenarios import Cell, run_cell
from hbbft_tpu.obs.flight import (
    DEFAULT_FLIGHT_EPOCHS,
    FLIGHT_EPOCHS_ENV,
    FlightRecorder,
    flight_epochs,
    load_bundle,
    summarize_bundle,
    validate_bundle,
    write_bundle,
)


def _commit_events(epoch, base):
    # a crank tick opens the window, RBC lands 8 cranks later (the
    # longest stretch — it gates), the commit closes 1 crank after
    return [
        {"phase": "crank", "node": None, "instance": None, "round": None,
         "epoch": None, "crank": base, "now": base},
        {"phase": "rbc.output", "node": 0, "instance": 0, "round": None,
         "epoch": None, "crank": base + 8, "now": base + 8},
        {"phase": "epoch.commit", "node": 0, "instance": None, "round": None,
         "epoch": epoch, "crank": base + 9, "now": base + 9},
    ]


def _filled(epochs=12, ring=None):
    fr = FlightRecorder(epochs=ring, context={"cell": {"n": 4, "seed": 1}})
    for e in range(epochs):
        fr.record(e, series_row={"epoch": e}, events=_commit_events(e, e * 100))
    return fr


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------


def test_ring_keeps_last_k_epochs():
    fr = _filled(epochs=12, ring=4)
    assert [f["epoch"] for f in fr.frames] == [8, 9, 10, 11]
    assert fr.recorded == 12


def test_ring_size_from_env(monkeypatch):
    monkeypatch.delenv(FLIGHT_EPOCHS_ENV, raising=False)
    assert flight_epochs() == DEFAULT_FLIGHT_EPOCHS
    monkeypatch.setenv(FLIGHT_EPOCHS_ENV, "3")
    assert flight_epochs() == 3
    assert FlightRecorder().epochs == 3
    monkeypatch.setenv(FLIGHT_EPOCHS_ENV, "junk")
    assert flight_epochs() == DEFAULT_FLIGHT_EPOCHS
    monkeypatch.setenv(FLIGHT_EPOCHS_ENV, "-2")
    assert flight_epochs() == DEFAULT_FLIGHT_EPOCHS


# ---------------------------------------------------------------------------
# bundles
# ---------------------------------------------------------------------------


def test_bundle_is_valid_and_reconstructs_gates():
    doc = _filled(ring=4).bundle("verdict_failure")
    assert validate_bundle(doc) == []
    cp = doc["critical_path"]
    assert cp["gate"] == "epoch 11 gated by RBC(0) output on node 0"
    assert [p["epoch"] for p in cp["paths"]] == [8, 9, 10, 11]
    assert cp["gating"] == {"rbc.output": 1.0}


def test_gate_hint_used_when_no_commits_in_window():
    fr = FlightRecorder(epochs=2)
    fr.record(0, events=[{"phase": "crank", "crank": 1, "now": 1}])
    doc = fr.bundle("crank_error", gate_hint="BA(2) short of coin shares")
    assert doc["critical_path"]["gate"] == "BA(2) short of coin shares"
    assert doc["critical_path"]["paths"] == []


def test_write_load_roundtrip(tmp_path):
    doc = _filled(ring=2).bundle(
        "crank_error", why={"summary": ["stuck"]}, faults=[(0, 1, "crash:x")]
    )
    path = write_bundle(doc, str(tmp_path / "b.forensics.json"))
    loaded = load_bundle(path)
    assert validate_bundle(loaded) == []
    assert loaded["reason"] == "crank_error"
    assert loaded["faults"] == [[0, 1, "crash:x"]]


def test_validate_rejects_malformed_bundles():
    good = _filled(ring=2).bundle("crank_error")
    assert validate_bundle("nope") == ["bundle is not a JSON object"]
    missing = {k: v for k, v in good.items() if k != "frames"}
    assert validate_bundle(missing) == ["missing key 'frames'"]
    bad = json.loads(json.dumps(good))
    bad["frames"] = [{"epoch": 5}, {"epoch": 3}]
    assert any("not monotonic" in e for e in validate_bundle(bad))
    bad = json.loads(json.dumps(good))
    bad["critical_path"]["gating"] = {"rbc.echo": 1.0}
    assert any("not in critpath.PHASES" in e for e in validate_bundle(bad))
    bad = json.loads(json.dumps(good))
    bad["critical_path"]["gating"] = {"rbc.output": 0.4}
    assert any("sum to" in e for e in validate_bundle(bad))


def test_summary_lines_name_reason_and_gate():
    doc = _filled(ring=4).bundle(
        "verdict_failure", faults=[(0, 2, "crash:replay_divergence")]
    )
    lines = summarize_bundle(doc)
    assert "reason='verdict_failure'" in lines[0]
    assert any("gate: epoch 11 gated by" in ln for ln in lines)
    assert any("fault crash:replay_divergence: 1" in ln for ln in lines)


# ---------------------------------------------------------------------------
# run_cell integration: the auto-dump
# ---------------------------------------------------------------------------

_FAIL_CELL = Cell(
    attack="equivocate", schedule="partition_heal", churn="era_flip",
    crash="one_restart", traffic="one_x", n=5, epochs=12, seed=3,
)


def test_failing_cell_autodumps_bundle_naming_injected_fault():
    # starve the crank budget just past the crash/restart (the soak
    # --smoke-fail calibration): the run dies on CrankError and the
    # flight recorder's bundle must name the injected fault's phase
    r = run_cell(_FAIL_CELL, crank_limit=4200)
    assert not r.ok
    assert r.forensics is not None
    assert validate_bundle(r.forensics) == []
    assert r.forensics["reason"] == "crank_error"
    assert "crash:recovery" in r.forensics["critical_path"]["gating"]
    assert any(
        p["gate_phase"] == "crash:recovery"
        for p in r.forensics["critical_path"]["paths"]
    )


def test_passing_cell_emits_no_bundle():
    r = run_cell(
        Cell(
            attack="passive", schedule="uniform", churn="none",
            crash="none", traffic="none", n=4, epochs=6, seed=2,
        )
    )
    assert r.ok and r.forensics is None


def test_bundle_replays_bit_identically():
    a = run_cell(_FAIL_CELL, crank_limit=4200)
    b = run_cell(_FAIL_CELL, crank_limit=4200)
    dump = lambda r: json.dumps(r.forensics, sort_keys=True, default=repr)
    assert dump(a) == dump(b)


def test_snapshot_detaches_obs_attrs_but_live_ring_survives():
    # whole-net checkpoint taken mid-run: critpath/metrics_log are
    # environment (evidence collectors), not consensus state — the
    # snapshot drops them, the restored net boots without them, and the
    # ORIGINAL net's recorder keeps its ring intact
    from hbbft_tpu.crypto.backend import MockBackend
    from hbbft_tpu.net.virtual_net import NetBuilder
    from hbbft_tpu.obs.critpath import CritPathRecorder
    from hbbft_tpu.obs.timeseries import MetricsLog
    from hbbft_tpu.protocols.queueing_honey_badger import (
        QueueingHoneyBadgerBuilder,
    )
    from hbbft_tpu.protocols.sender_queue import SenderQueue
    from hbbft_tpu.utils.snapshot import load_node, save_node

    def make(ni, be, rng):
        return SenderQueue(
            QueueingHoneyBadgerBuilder(ni, be, rng).batch_size(3).build()
        )

    net = (
        NetBuilder(range(4)).backend(MockBackend()).using(make).build(seed=5)
    )
    net.critpath = CritPathRecorder()
    net.metrics_log = MetricsLog()
    net.critpath.stamp("crank", node=0)
    net.metrics_log.snap(0)
    restored = load_node(save_node(net), MockBackend())
    assert restored.critpath is None and restored.metrics_log is None
    assert len(net.critpath.events) == 1 and len(net.metrics_log) == 1
