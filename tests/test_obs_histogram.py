"""Histogram unit tests: bucketing error bound, percentile summaries."""

import random

from hbbft_tpu.obs.histogram import SUBBUCKETS, Histogram


def test_empty_histogram_summary():
    h = Histogram("empty")
    assert h.summary() == {"count": 0}
    assert h.percentile(50) == 0.0
    assert h.mean == 0.0
    assert len(h) == 0


def test_exact_fields_are_exact():
    h = Histogram()
    for v in (3.0, 7.0, 1.0, 100.0):
        h.record(v)
    assert h.count == 4
    assert h.min == 1.0
    assert h.max == 100.0
    assert h.mean == (3 + 7 + 1 + 100) / 4


def test_percentiles_uniform_within_bucket_error():
    h = Histogram()
    for v in range(1, 10_001):
        h.record(float(v))
    # log-bucket relative error bound: 1/SUBBUCKETS plus the midpoint
    # placement; 2/SUBBUCKETS is a safe envelope
    tol = 2.0 / SUBBUCKETS
    for p, expect in ((50, 5000), (90, 9000), (99, 9900)):
        got = h.percentile(p)
        assert abs(got - expect) <= expect * tol, (p, got)
    s = h.summary()
    assert s["count"] == 10_000
    assert s["min"] == 1.0 and s["max"] == 10_000.0
    assert s["p50"] <= s["p90"] <= s["p99"]


def test_percentile_clamps_to_extremes():
    h = Histogram()
    h.record(5.0)
    # single sample: every percentile is that sample
    assert h.percentile(0) == 5.0
    assert h.percentile(50) == 5.0
    assert h.percentile(100) == 5.0


def test_subunit_and_power_of_two_values():
    h = Histogram()
    vals = [0.001, 0.25, 0.5, 1.0, 2.0, 4.0, 1024.0, 1 << 40]
    for v in vals:
        h.record(v)
    assert h.count == len(vals)
    assert h.min == 0.001 and h.max == float(1 << 40)
    # bucket of an exact power of two must not land an octave off
    for v in (1.0, 2.0, 4.0, 1024.0):
        b = Histogram._bucket(v)
        rep = Histogram._bucket_value(b)
        assert v <= rep <= v * (1.0 + 2.0 / SUBBUCKETS), (v, rep)


def test_negative_values_clamp_to_zero():
    h = Histogram()
    h.record(-3.0)
    assert h.count == 1
    assert h.min == 0.0


def test_random_stream_percentile_error_bound():
    rng = random.Random(7)
    h = Histogram()
    samples = sorted(rng.uniform(1.0, 1e6) for _ in range(5000))
    for v in samples:
        h.record(v)
    tol = 2.0 / SUBBUCKETS
    for p in (50, 90, 99):
        exact = samples[min(len(samples) - 1, int(len(samples) * p / 100))]
        got = h.percentile(p)
        assert abs(got - exact) <= exact * tol + 1e-9, (p, got, exact)
