"""Test configuration.

Multi-chip sharding tests run on a virtual 8-device CPU mesh: real
multi-chip TPU hardware is not available in CI, so JAX is forced onto the
host platform with 8 virtual devices (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).

The ambient environment may register a remote-TPU PJRT plugin via a
sitecustomize hook that imports jax at interpreter startup, so setting
JAX_PLATFORMS via os.environ here is too late — the platform must be forced
through jax.config instead (XLA_FLAGS is still read lazily at backend init).
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# NOTE: enable_compile_cache() is a deliberate no-op on the CPU platform —
# XLA:CPU's AOT cache entries fail the loader's host-feature check even on
# the host that wrote them (warn-then-SIGILL / hard abort; two pytest runs
# died that way 2026-07-30, see utils/jax_config.py).  The suite therefore
# recompiles per run; keep per-test graph sizes small.
from hbbft_tpu.utils.jax_config import (  # noqa: E402
    enable_compile_cache,
    raise_stack_limit,
)

enable_compile_cache()
# XLA:CPU compiles the big RLC/pairing graphs with deeply recursive LLVM
# passes on the main thread; the default 8 MB stack segfaults
# nondeterministically (see utils/jax_config.raise_stack_limit).
raise_stack_limit()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: slow tests (Python pairings)")


def pytest_collection_modifyitems(config, items):
    """Run the heavy-XLA-compile tests FIRST.

    XLA:CPU segfaults compiling the big RLC verification graphs late in
    a long pytest process (observed 6/6 full-suite runs on 2026-07-30,
    always at an RLC compile ~45 min in), while the same tests pass
    consistently as young solo processes (3/3).  Whatever accumulated
    process state triggers the compiler bug, compiling the big graphs
    early — before hundreds of other compilations — avoids it.
    """
    heavy = (
        "test_rlc_verify",
        "test_tpu_backend",
        "test_mesh_backend",
        "test_honey_badger_tpu",
        # big eager tower/pairing graphs; observed segfaulting ~66 min into
        # a full run (2026-07-30) while passing consistently when young
        "test_pairing_fused",
        "test_curve_fused",
    )
    items.sort(
        key=lambda it: 0 if any(h in it.nodeid for h in heavy) else 1
    )
