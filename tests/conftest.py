"""Test configuration.

Multi-chip sharding tests run on a virtual 8-device CPU mesh: real
multi-chip TPU hardware is not available in CI, so JAX is forced onto the
host platform with 8 virtual devices (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: slow tests (Python pairings)")
