"""Test configuration.

Multi-chip sharding tests run on a virtual 8-device CPU mesh: real
multi-chip TPU hardware is not available in CI, so JAX is forced onto the
host platform with 8 virtual devices (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).

The ambient environment may register a remote-TPU PJRT plugin via a
sitecustomize hook that imports jax at interpreter startup, so setting
JAX_PLATFORMS via os.environ here is too late — the platform must be forced
through jax.config instead (XLA_FLAGS is still read lazily at backend init).
"""

import os
import sys

import pytest

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# NOTE: enable_compile_cache() is a deliberate no-op on the CPU platform —
# XLA:CPU's AOT cache entries fail the loader's host-feature check even on
# the host that wrote them (warn-then-SIGILL / hard abort; two pytest runs
# died that way 2026-07-30, see utils/jax_config.py).  The suite therefore
# recompiles per run; keep per-test graph sizes small.
from hbbft_tpu.utils.jax_config import (  # noqa: E402
    enable_compile_cache,
    raise_stack_limit,
)

enable_compile_cache()
# XLA:CPU compiles the big RLC/pairing graphs with deeply recursive LLVM
# passes on the main thread; the default 8 MB stack segfaults
# nondeterministically (see utils/jax_config.raise_stack_limit).
raise_stack_limit()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: slow tests (Python pairings)")


# ---------------------------------------------------------------------------
# Subprocess isolation for the XLA:CPU-segfault-prone modules.
#
# The big RLC/pairing graph compiles crash the XLA:CPU compiler
# NONDETERMINISTICALLY (observed at minute 15 of a fresh run and at minute
# 66 of an ordered one; always inside backend_compile); heavy-first
# ordering and the RLIMIT_STACK raise reduced but did not eliminate it.
# Each module below therefore runs in its own young pytest subprocess —
# one crash kills only that module's attempt, and a crashed attempt (rc
# < 0 or 139/134) is retried once, converting the flaky crash into a
# green run.  Per-test results are read back from junitxml and reported
# into this session, so -x/-q/exit codes behave normally.
# ---------------------------------------------------------------------------

_ISOLATE_DEFAULT = (
    "tests/test_rlc_verify.py",
    "tests/test_tpu_backend.py",
    "tests/test_mesh_backend.py",
    "tests/test_honey_badger_tpu.py",
)


def _isolate_modules():
    env = os.environ.get("HBBFT_ISOLATE_MODULES")
    if env is not None:
        return tuple(m for m in env.split(",") if m)
    return _ISOLATE_DEFAULT


_isolated_selected = {}  # module path -> [nodeid, ...] selected in THIS run


@pytest.hookimpl(trylast=True)
def pytest_collection_modifyitems(config, items):
    """Run the heavy (isolated-subprocess) modules FIRST so their
    failures surface early and the light tests stream afterwards; record
    which of their tests survived -k/-m/nodeid selection so the
    subprocess runs exactly those.

    ``trylast`` matters: conftest hookimpls run BEFORE the builtin mark
    plugin's, so a plain impl here saw the PRE-deselection item list and
    recorded ``-m 'not slow'``-excluded nodeids into the subprocess run
    (the subprocess gets explicit nodeids, which override markers) —
    tier-1 silently re-included every slow test in the heavy set and
    blew the 870 s window.  trylast runs after deselect_by_mark, so only
    the surviving items are recorded."""
    heavy = tuple(os.path.basename(m).removesuffix(".py") for m in _isolate_modules())
    items.sort(
        key=lambda it: 0 if any(h in it.nodeid for h in heavy) else 1
    )
    for it in items:
        mod = _module_path(it)
        if mod in _isolate_modules():
            _isolated_selected.setdefault(mod, []).append(it.nodeid)


_isolated_results = {}
_isolated_ran = set()


def _module_path(item) -> str:
    path = item.nodeid.split("::")[0]
    return path.replace(os.sep, "/")


def _junit_key(nodeid: str) -> tuple:
    """(classname, name) as pytest's junitxml records this nodeid:
    'tests/test_x.py::TestFoo::test_bar[p]' →
    ('tests.test_x.TestFoo', 'test_bar[p]')."""
    parts = nodeid.split("::")
    mod = parts[0].replace("/", ".").replace(os.sep, ".")
    mod = mod.removesuffix(".py")
    cls = ".".join([mod] + parts[1:-1])
    return (cls, parts[-1])


def _run_module_isolated(mods) -> None:
    """Run the selected tests of ``mods`` (a list of module paths) in ONE
    young subprocess.  One process for the whole heavy set: the modules
    share a single jax import and one in-process jit cache (the grouped
    RLC / pairing / ladder graphs overlap heavily across them), which
    buys back minutes against the tier-1 budget compared to one process
    per module.  Crash containment is unchanged in kind — a crash kills
    only this attempt and is retried once — just with the heavy set as
    the blast radius instead of one module."""
    import subprocess
    import tempfile
    import xml.etree.ElementTree as ET

    env = dict(os.environ)
    env["HBBFT_ISOLATED"] = "1"
    targets = [t for m in mods for t in (_isolated_selected.get(m) or [m])]
    with tempfile.NamedTemporaryFile(suffix=".xml", delete=False) as tf:
        xml_path = tf.name
    try:
        proc = None
        timed_out = False
        for attempt in (1, 2):
            try:
                proc = subprocess.run(
                    [
                        sys.executable,
                        "-m",
                        "pytest",
                        *targets,
                        "-q",
                        "--tb=long",
                        f"--junit-xml={xml_path}",
                    ],
                    cwd=_REPO,
                    env=env,
                    capture_output=True,
                    text=True,
                    timeout=5400,
                )
            except subprocess.TimeoutExpired:
                # A hang would hang again — record, don't retry or raise
                # (an uncaught exception here INTERNALERRORs the session).
                timed_out = True
                break
            crashed = proc.returncode not in (0, 1, 2, 5)
            if not crashed:
                break
            sys.stderr.write(
                f"\n[conftest] isolated {' '.join(mods)} crashed "
                f"(rc={proc.returncode}), attempt {attempt}/2\n"
            )
        if timed_out:
            for mod in mods:
                _isolated_results[mod] = (
                    "crashed",
                    f"isolated subprocess for {mod} exceeded 5400s "
                    "(hung compile?)",
                    0.0,
                )
            return
        tail = (proc.stdout + proc.stderr)[-8000:]
        try:
            tree = ET.parse(xml_path)
        except ET.ParseError:
            tree = None
        if tree is not None:
            for case in tree.iter("testcase"):
                key = (case.get("classname", ""), case.get("name", ""))
                dur = float(case.get("time") or 0.0)
                if case.find("failure") is not None or case.find("error") is not None:
                    el = case.find("failure")
                    if el is None:
                        el = case.find("error")
                    _isolated_results[key] = (
                        "failed",
                        (el.get("message") or "") + "\n" + (el.text or ""),
                        dur,
                    )
                elif case.find("skipped") is not None:
                    el = case.find("skipped")
                    _isolated_results[key] = (
                        "skipped",
                        el.get("message") or "skipped",
                        dur,
                    )
                else:
                    _isolated_results[key] = ("passed", "", dur)
        crashed = proc.returncode not in (0, 1, 2, 5)
        if crashed or tree is None:
            for mod in mods:
                _isolated_results[mod] = (
                    "crashed",
                    f"isolated subprocess rc={proc.returncode}\n{tail}",
                    0.0,
                )
    finally:
        try:
            os.unlink(xml_path)
        except OSError:
            pass


def pytest_runtest_protocol(item, nextitem):
    from _pytest.reports import TestReport

    mod = _module_path(item)
    if os.environ.get("HBBFT_ISOLATED") or mod not in _isolate_modules():
        return None
    if mod not in _isolated_ran:
        # first isolated test reached: run the WHOLE heavy set in one
        # subprocess (shared jax import + jit caches across modules)
        pending = [
            m
            for m in _isolate_modules()
            if m not in _isolated_ran and _isolated_selected.get(m)
        ]
        _isolated_ran.update(pending)
        _run_module_isolated(pending)

    crash = _isolated_results.get(mod)
    res = _isolated_results.get(_junit_key(item.nodeid))
    if res is None:
        # not in the junitxml (module crashed before reaching it)
        res = (
            "failed",
            crash[1] if crash else "missing from isolated run",
            0.0,
        )
    outcome, text, dur = res

    item.ihook.pytest_runtest_logstart(
        nodeid=item.nodeid, location=item.location
    )
    if outcome == "skipped":
        longrepr = (mod, 0, text)
    elif outcome == "failed":
        longrepr = text
    else:
        longrepr = None
    report = TestReport(
        nodeid=item.nodeid,
        location=item.location,
        keywords={item.name: 1},
        outcome=outcome if outcome != "crashed" else "failed",
        longrepr=longrepr,
        when="setup" if outcome == "skipped" else "call",
        sections=[],
        duration=dur,
        start=0.0,
        stop=dur,
    )
    item.ihook.pytest_runtest_logreport(report=report)
    item.ihook.pytest_runtest_logfinish(
        nodeid=item.nodeid, location=item.location
    )
    return True
